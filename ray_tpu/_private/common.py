"""Shared data model: task/actor specs, resource sets, config.

Analog of the reference's src/ray/common/ (TaskSpec task/task_spec.h, fixed-point
resource arithmetic scheduling/fixed_point.h, RayConfig ray_config_def.h). Specs
are msgpack-serializable dicts with typed wrappers; resources use integer
fixed-point (1/10000 granularity) so fractional grants never drift.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Config. Pattern follows the reference's RAY_CONFIG table: every knob is
# overridable via environment variable RAY_TPU_<NAME>.
# ---------------------------------------------------------------------------

_CONFIG_DEFAULTS: Dict[str, Any] = {
    # Objects at or below this size live in the owner's in-process memory
    # store and move inline through RPCs; larger go to the shm store.
    "max_direct_call_object_size": 100 * 1024,
    # Default object store capacity fraction of system memory.
    "object_store_memory_fraction": 0.3,
    "object_store_memory_min": 64 * 1024 * 1024,
    # Worker lease / pool.
    "worker_lease_timeout_s": 60.0,
    # Zygote fork / worker process start: how long the raylet waits for the
    # forked pid before declaring the spawn wedged.
    "worker_start_timeout_s": 60.0,
    "idle_worker_keep_s": 60.0,
    # How long an owner's idle leases park before returning to the raylet.
    # Bursty submitters reuse the full worker set across bursts; other
    # clients (and autoscaler idle scale-down) wait at most this long for
    # the pinned resources (in-flight lease requests force immediate
    # return).
    "worker_lease_idle_keep_s": 0.5,
    "max_workers_per_node": 64,
    # Health checks (reference cadence: ray_config_def.h:847-853). The GCS
    # actively Pings every ALIVE node each period; `threshold` consecutive
    # misses mark it DEAD (catches wedged-but-connected raylets). period 0
    # disables active probing (connection loss still triggers death).
    "health_check_initial_delay_s": 5.0,
    "health_check_period_s": 3.0,
    "health_check_timeout_s": 10.0,
    "health_check_failure_threshold": 5,
    # Pubsub: per-subscriber bounded queue length; a subscriber falling this
    # far behind starts losing its OLDEST messages (publisher.h analog).
    "pubsub_max_buffered_msgs": 1000,
    # Task defaults.
    "default_max_task_retries": 3,
    "actor_default_max_restarts": 0,
    # Lineage reconstruction: how many times a lost task-return object may be
    # recomputed by re-running its producing task (reference:
    # object_recovery_manager.h + task_manager.cc lineage bookkeeping).
    "max_lineage_reconstruction": 3,
    # Object transfer chunk size between nodes (the floor: adaptive sizing
    # scales the chunk with the object, see adaptive_chunk_size()).
    "object_chunk_size": 8 * 1024 * 1024,
    # Adaptive chunk cap: huge transfers use chunks up to this size so a
    # multi-GiB object doesn't pay per-chunk drain/round-trip overhead
    # hundreds of times. Blob frames stream chunks zero-copy, so a bigger
    # chunk costs no extra buffering on the send side.
    "object_chunk_size_max": 64 * 1024 * 1024,
    # Arena eviction: unpinned objects accessed within this window are never
    # evicted (their arena bytes could still be mid-read by a client).
    "object_store_eviction_grace_s": 10.0,
    # Scheduling: hybrid policy spills beyond this utilization (reference
    # scheduler_spread_threshold).
    "scheduler_spread_threshold": 0.5,
    "scheduler_top_k_fraction": 0.2,
    # Cluster-view delta batching: the GCS coalesces node resource/membership
    # changes for this long before publishing one versioned delta on
    # "syncer:nodes". 0 publishes immediately (one delta per mutation); at
    # hundreds of nodes batching caps the broadcast fan-out at
    # subscribers/batch_ms msgs/s instead of subscribers*grants/s.
    "scheduler_view_batch_ms": 0,
    # Raylet -> GCS UpdateResources debounce: once the dirty flag is set, a
    # raylet waits this long before reporting so a burst of grant/release
    # mutations folds into one round-trip instead of one each. 0 reports
    # per mutation (pre-PR-20 behavior); the idle 1 s heartbeat report is
    # unaffected either way.
    "raylet_report_debounce_s": 0.01,
    # Object spilling (reference: local_object_manager.cc +
    # external_storage.py): sealed objects are written to disk when the shm
    # arena fills and restored on access. Empty dir -> default under /tmp.
    "object_spilling_dir": "",
    # JSON spilling config selecting a registered external-storage backend,
    # e.g. '{"type": "filesystem", "params": {"directory_path": "/mnt/x"}}'
    # (reference: RAY_object_spilling_config). Empty -> filesystem under
    # object_spilling_dir.
    "object_spilling_config": "",
    # Spill/restore IO thread-pool width (reference: max_io_workers,
    # ray_config_def.h). IO runs off the raylet event loop so multi-GiB
    # spills never stall lease grants or RPCs.
    "max_io_workers": 4,
    # Bounded wait for the spill/restore IO pool to drain at node shutdown
    # (a wedged storage backend must not hang shutdown forever).
    "io_pool_shutdown_timeout_s": 10.0,
    # Proactive pressure loop: when arena occupancy exceeds this fraction the
    # raylet spills sealed-and-unpinned objects (largest-first) down to the
    # threshold without waiting for an allocation failure (reference:
    # object_spilling_threshold, ray_config_def.h). <= 0 disables the loop;
    # allocation-failure spilling still runs either way.
    "object_spilling_threshold": 0.8,
    # Pressure-loop poll interval.
    "object_spilling_poll_interval_s": 0.25,
    # Owner-side lineage cache budget: producing TaskSpecs retained for
    # reconstruction, LRU-pruned beyond this many bytes (reference:
    # RAY_max_lineage_bytes / lineage_pinning). Reconstruction of a pruned
    # object raises ObjectReconstructionFailedError.
    "lineage_bytes_limit": 64 * 1024 * 1024,
    # Cap on recursive lineage reconstruction: rebuilding a lost object may
    # find its producer's arguments also lost; each nesting level counts
    # toward this depth before the owner gives up with a typed error.
    "reconstruction_max_depth": 10,
    # serve: how long the controller waits for a replica to acknowledge a
    # user_config reconfigure before replacing it.
    "serve_reconfigure_timeout_s": 30.0,
    # serve: default end-to-end request budget the proxy stamps on ingress
    # requests (overridable per request via the serve-request-timeout-s
    # header). Rides the RPC TTL frames, so every downstream hop shrinks it.
    "serve_request_timeout_s": 60.0,
    # serve: default per-deployment router queue-depth cap (requests waiting
    # for a replica slot). Overflow sheds immediately with a typed
    # DeploymentOverloadedError, bounding memory under open-loop storms.
    # Per-deployment override: DeploymentConfig.max_queued_requests.
    "serve_max_queued_requests": 200,
    # serve: EWMA smoothing factor for the router's per-deployment service
    # time estimate (admission control sheds requests whose remaining
    # deadline budget cannot cover the estimate).
    "serve_admission_ewma_alpha": 0.2,
    # serve: admission safety factor — a request is shed unless its
    # remaining budget >= estimate * factor, so near-deadline requests
    # don't burn a replica slot only to be cut at the wire deadline.
    "serve_admission_safety_factor": 1.5,
    # serve: how often each router pushes queue-depth/ongoing metrics to the
    # controller (feeds the queue-driven autoscaler).
    "serve_router_metrics_interval_s": 0.5,
    # serve: how long a backpressured request waits for a freed replica slot
    # between admission re-checks.
    "serve_backpressure_poll_s": 0.5,
    # serve: controller-side timeout for one replica get_metrics sample.
    "serve_metrics_sample_timeout_s": 2.0,
    # serve: grace added on top of graceful_shutdown_timeout_s before the
    # controller force-kills a draining replica.
    "serve_shutdown_grace_s": 5.0,
    # serve: long-poll listen timeout (controller holds a listen open this
    # long before replying empty; clients immediately re-listen).
    "serve_long_poll_timeout_s": 30.0,
    # Create-request backpressure: how long ObjCreate waits for spill/eviction
    # to make room before failing (plasma create_request_queue.cc analog).
    "object_store_create_timeout_s": 30.0,
    # Task-event ring: max buffered owner-side task events between 1 Hz GCS
    # flushes; oldest drop first (reference: task_events_max_num_... knobs).
    "task_events_max_buffer": 10000,
    # Worker-side per-task profile events (deserialize/execute/store phase
    # timings in the chrome timeline). Off by default like the reference's
    # RAY_PROFILING — it adds one GCS event per task.
    "task_profile_events": False,
    # Native direct-call task channel (src/fastpath.cc): eligible
    # dependency-free tasks ride a C++-owned socket past the asyncio/msgpack
    # RPC stack (reference: the C++ direct task transport,
    # direct_task_transport.h:75). Auto-disabled per task when tracing or
    # profile events need the RPC path's instrumentation.
    "fastpath_enabled": True,
    # Max bytes of concurrent inbound object transfers a raylet admits
    # (reference: pull_manager.h bandwidth-capped pulls). Head-of-line
    # pulls exceed it rather than deadlock.
    "pull_max_bytes_in_flight": 256 * 1024 * 1024,
    # Inbound push-stream stall detection: a pull whose chunk assembly makes
    # no progress for this long (source died mid-push, chunks lost on a bad
    # link) aborts the assembly and re-requests the push instead of waiting
    # out the full blocking-get timeout + the 60s assembly janitor.
    "pull_stall_timeout_s": 5.0,
    # How many times a stalled push stream is re-requested before the pull
    # falls back to the request/reply chunk loop.
    "pull_max_rerequests": 2,
    # Fork workers from a preloaded zygote process (reference:
    # worker_pool.cc prestart) instead of cold `python -m` spawns —
    # ~10ms vs ~0.5-1.5s per worker, the difference between seconds and
    # minutes when a thousand actors start at once.
    "worker_zygote_enabled": True,
    # OTel-style task tracing spans with context propagation (reference:
    # ray.init(_tracing_startup_hook) + tracing_helper.py). Off by default.
    "task_trace_spans": False,
    # Sampled always-on tracing: fraction of new root traces recorded when
    # task_trace_spans is off (0.0 disables). The sampling decision is
    # deterministic on the root id, so every process on a request's path
    # independently agrees whether the trace exists (docs/observability.md
    # "Distributed tracing").
    "trace_sample_rate": 0.0,
    # Runtime-span ring: max spans buffered per process between flushes to
    # the GCS spans ring; oldest drop first (same shape as
    # task_events_max_buffer).
    "trace_span_buffer": 8192,
    # Push manager: max chunks in flight across ALL destination pushes from
    # one node (reference: push_manager.h max_chunks_in_flight). With 8 MiB
    # chunks the default bounds broadcast buffering at ~64 MiB.
    "push_manager_max_chunks": 8,
    # Memory monitor (reference: memory_monitor.h:52 + worker_killing_policy):
    # kill the newest leased worker when system memory use crosses the
    # threshold. interval 0 disables.
    "memory_monitor_interval_s": 1.0,
    "memory_usage_threshold": 0.95,
    # Pre-fault the shm arena's pages at raylet startup (background thread):
    # first-touch page allocation otherwise dominates large-object put latency
    # (~17 ms per 16 MiB on tmpfs). Off by default — it commits the whole
    # arena's physical memory and burns CPU proportional to capacity; prompt
    # free-span reuse makes steady-state puts hit warm pages anyway. Enable on
    # dedicated TPU hosts for cold-start-sensitive pipelines.
    "prefault_object_store": False,
    # GCS fault tolerance: persist control-plane state to a session-scoped
    # sqlite file so a restarted GCS resumes with its actor/PG/KV/job tables
    # intact (reference: RedisStoreClient, redis_store_client.h:33). Cheap
    # (WAL write-through of few-hundred-byte records); disable for pure
    # in-memory control planes.
    "gcs_persistence": True,
    # Which durable store backs the GCS when persistence is on
    # (gcs_store.py): "wal" — append-only CRC-framed log with group commit
    # (one fsync per loop tick of mutations) and snapshot compaction;
    # "sqlite" — write-through WAL-mode sqlite rows; "memory" — no
    # durability even with a persist path (testing).
    "gcs_persist_backend": "wal",
    # Durability/sync policy for the durable backends
    # (docs/fault_tolerance.md): "batch" — group-commit fsync per loop tick
    # (wal) / sqlite synchronous=NORMAL (an OS crash can lose the last
    # tick / the commits since the last WAL checkpoint; a process crash
    # loses nothing); "always" — fsync per record (wal) / synchronous=FULL;
    # "off" — never fsync (page cache only).
    "gcs_store_sync": "batch",
    # WAL log-size threshold that triggers snapshot compaction (the full
    # table state is rewritten as one frame and the log truncated).
    "gcs_wal_compact_bytes": 4 * 1024 * 1024,
    # ---- HA control plane (gcs_ha.py, docs/fault_tolerance.md §HA). ----
    # Follower count for gcs_persist_backend=replicated. The group (primary
    # + followers) acks a group commit once a majority of members —
    # ⌈(n+1)/2⌉, the primary's own append included — holds it durably;
    # laggard members catch up asynchronously (per-member lag is exported
    # as gcs_replica_lag_seq). Default 2 → a 3-member group that tolerates
    # one slow/partitioned/lost member without stalling commits. With 1
    # follower the quorum is 2-of-2, i.e. the original synchronous
    # wait-for-all shipping.
    "gcs_replication_followers": 2,
    # How the warm standby receives the shipped stream (gcs_ha.py):
    # "rpc" — subscribe to the leader over ShipFrames/ShipSnapshot wire
    # RPCs (works across OS processes/hosts; falls back to file tailing
    # while the leader is unreachable); "file" — tail a follower log on
    # shared storage (original in-process mode).
    "gcs_standby_mode": "rpc",
    # Leadership lease duration. The leader re-asserts its leadership
    # record every lease/3; a standby promotes when the record's deadline
    # is this far in the past (plus one grace interval to absorb clock
    # skew between renew and tail-observation).
    "gcs_leader_lease_s": 2.0,
    # How often the warm standby polls the replicated log tail for new
    # frames and leadership-record changes.
    "gcs_standby_poll_s": 0.1,
    # Path of the leader pointer file ("host port\n", atomically replaced
    # on promotion) that cross-process clients resolve before re-dialing.
    # Empty → derived as <persist_path>.leader next to the store.
    "gcs_leader_file": "",
    # Echo captured worker stdout/stderr to the driver (reference:
    # ray.init(log_to_driver=True) + log_monitor.py streaming).
    "log_to_driver": True,
    # How long a caller waits for a PENDING/RESTARTING actor to come up
    # before failing the call (reference: gcs_client actor resolution).
    "actor_resolve_timeout_s": 300.0,
    # ---- RPC resilience budgets (reference: retryable_grpc_client.h +
    # gcs_rpc_client.h; every knob below replaces a former call-site
    # literal, enforced by the rpc-magic-timeout lint rule). ----
    # Control-plane probes and cancels (KillWorker, CancelWorkerLease,
    # CancelTask): quick request/reply, fail fast.
    "rpc_control_timeout_s": 10.0,
    # GCS-driven actor placement round trip (LeaseWorkerForActor): covers
    # lease queueing + worker spawn + CreateActor on the worker.
    "rpc_lease_timeout_s": 120.0,
    # Placement-group 2PC legs (Prepare/Commit/ReleasePGBundles).
    "rpc_pg_timeout_s": 30.0,
    # Raylet -> worker CreateActor (cold spawn + user __init__).
    "rpc_actor_create_timeout_s": 300.0,
    # Whole-object push between raylets (PushObject request/reply).
    "rpc_transfer_timeout_s": 120.0,
    # Per-chunk / per-stream-start transfers (FetchChunk, PushStart).
    "rpc_chunk_timeout_s": 60.0,
    # Client -> local raylet pull of a remote object (PullObject).
    "rpc_pull_timeout_s": 300.0,
    # Bulk senders' per-chunk TCP drain wait (push_manager): a destination
    # that keeps the socket above the high-water mark this long is wedged.
    "rpc_drain_timeout_s": 30.0,
    # Blocking ObjGet a puller falls back to when PullObject returned no
    # mapping (e.g. the seal is still in flight on the owner's connection).
    "rpc_object_get_timeout_s": 30.0,
    # Optional per-attempt cap on the retryable GCS channel: a lost reply
    # is re-issued (idempotent methods only) after this long instead of
    # riding out the caller's whole budget. 0 disables (production
    # default — the GCS channel carries long-polls like CreateActor
    # wait_alive); the chaos latency suite enables it.
    "rpc_default_timeout_s": 0.0,
    # Dial backoff (rpc.connect): full-jitter exponential, total-time cap.
    "rpc_dial_initial_backoff_s": 0.05,
    "rpc_dial_max_backoff_s": 1.0,
    "rpc_dial_total_s": 3.0,
    # Call-retry backoff (RetryableConnection) and the total budget a
    # caller waits out a GCS restart before the error surfaces.
    "rpc_retry_initial_backoff_s": 0.05,
    "rpc_retry_max_backoff_s": 2.0,
    "rpc_backoff_multiplier": 2.0,
    "rpc_reconnect_timeout_s": 30.0,
    # Deadline enforcement slack: a handler may finish (or unwind its
    # cancellation) this long past its wire deadline before the chaos
    # no-call-outlives-deadline invariant flags it.
    "rpc_deadline_grace_s": 0.5,
    # Event-loop implementation for daemons ("asyncio" | "uvloop").
    # "uvloop" installs the uvloop policy when the package is importable
    # and falls back to stock asyncio (with a log line) when it is not —
    # the A/B lives in `make perf`; see docs/perf.md "Native wire codec".
    "rpc_event_loop": "asyncio",
    # Worker subprocesses flush deadline_stats deltas (met/shed/enforced/
    # overruns) to the GCS at this cadence, plus once on Exit, so the
    # no-call-outlives-deadline invariant sees overruns inside
    # task-executing workers. 0 disables periodic reporting.
    "rpc_deadline_report_interval_s": 0.5,
    # Driver-side loop-thread bridge budgets (worker.py run_async): whole
    # cluster bring-up, and graceful shutdown before the loop is abandoned.
    "driver_bringup_timeout_s": 120.0,
    "driver_shutdown_timeout_s": 30.0,
    # ---- runtime telemetry plane (_private/telemetry.py). ----
    # Master switch for the per-process flush loops; the record hot paths
    # are unconditional (a bound-cell float add) and stay on regardless.
    "telemetry_enabled": True,
    # Per-process snapshot-and-reset flush cadence over ReportTelemetry.
    # 0 disables periodic flushing (exit flushes still run).
    "telemetry_flush_interval_s": 2.0,
    # Flight-recorder ring capacity (structured lifecycle events/process).
    "telemetry_flight_capacity": 4096,
    # A metrics snapshot (app-metric KV blob or telemetry gauge source)
    # older than this is treated as a dead process's leftovers: gauges are
    # dropped from /metrics and stale KV snapshots are GC'd.
    "metrics_stale_after_s": 30.0,
    # ---- Data-layer ingest pipeline (docs/perf.md "Ingest pipeline"). ----
    # How many block fetches iter_blocks/DataIterator keep in flight so
    # object-store pull overlaps batch assembly instead of serializing
    # against it. 1 reverts to serial get-per-block.
    "data_fetch_lookahead": 4,
    # streaming_split consumers iterate blocks in completion order (one
    # straggler read task delays only itself). Dataset-level iteration
    # (iter_batches/take/...) always preserves order regardless.
    "data_split_preserve_order": False,
}


class _Config:
    """Config table with RAY_TPU_* env overrides.

    Resolved values are cached on the instance (hot paths read config
    multiple times per task; an os.environ lookup per read costs ~1us
    each). Entry points that may run after test fixtures mutate the
    environment (ray_tpu.init, Cluster bring-up) call refresh().
    """

    def __getattr__(self, name: str):
        if name not in _CONFIG_DEFAULTS:
            raise AttributeError(name)
        env = os.environ.get(f"RAY_TPU_{name.upper()}")
        default = _CONFIG_DEFAULTS[name]
        if env is None:
            value = default
        elif isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        else:
            value = type(default)(env)
        self.__dict__[name] = value  # shadows __getattr__ until refresh()
        return value

    def refresh(self) -> None:
        self.__dict__.clear()


config = _Config()


def adaptive_chunk_size(total_size: int) -> int:
    """Transfer chunk size for an object of ``total_size`` bytes: the base
    ``object_chunk_size`` for small objects, scaling with the object (about
    a quarter of it) up to ``object_chunk_size_max``. Fewer, larger chunks
    amortize the per-chunk drain wait and control-frame overhead; blob
    framing keeps the send side zero-copy at any chunk size."""
    base = config.object_chunk_size
    cap = max(base, config.object_chunk_size_max)
    return max(base, min(cap, total_size // 4))


# ---------------------------------------------------------------------------
# Fixed-point resources (reference: src/ray/common/scheduling/fixed_point.h).
# ---------------------------------------------------------------------------

RESOURCE_UNIT = 10000  # 1.0 CPU == 10000 units


def to_fixed(amount: float) -> int:
    return int(round(amount * RESOURCE_UNIT))


def from_fixed(units: int) -> float:
    return units / RESOURCE_UNIT


class ResourceSet:
    """A bag of named resource quantities with exact arithmetic."""

    __slots__ = ("_units",)

    def __init__(self, amounts: Optional[Dict[str, float]] = None, _units=None):
        if _units is not None:
            self._units = {k: v for k, v in _units.items() if v != 0}
        else:
            self._units = {
                k: to_fixed(v) for k, v in (amounts or {}).items() if to_fixed(v) != 0
            }

    @classmethod
    def from_units(cls, units: Dict[str, int]) -> "ResourceSet":
        rs = cls.__new__(cls)
        rs._units = {k: v for k, v in units.items() if v != 0}
        return rs

    def to_units(self) -> Dict[str, int]:
        return dict(self._units)

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._units.items()}

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._units.get(k, 0) >= v for k, v in self._units.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            nv = units.get(k, 0) + v
            if nv:
                units[k] = nv
            else:
                units.pop(k, None)
        rs = ResourceSet.__new__(ResourceSet)
        rs._units = units
        return rs

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        units = dict(self._units)
        for k, v in other._units.items():
            nv = units.get(k, 0) - v
            if nv:
                units[k] = nv
            else:
                units.pop(k, None)
        rs = ResourceSet.__new__(ResourceSet)
        rs._units = units
        return rs

    def get(self, name: str) -> float:
        return from_fixed(self._units.get(name, 0))

    def is_empty(self) -> bool:
        return not self._units

    def nonnegative(self) -> bool:
        return all(v >= 0 for v in self._units.values())

    def keys(self):
        return self._units.keys()

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._units == other._units


# ---------------------------------------------------------------------------
# Specs. Kept as plain dicts on the wire; wrappers give attribute access.
# ---------------------------------------------------------------------------


@dataclass
class TaskSpec:
    """Everything a worker needs to execute one task invocation.

    Reference: TaskSpec proto (src/ray/protobuf/common.proto; max_task_retries
    at :666). args_blob is cloudpickle((args, kwargs)) with contained
    ObjectRefs reduced to descriptors; dependencies lists those refs so the
    executor resolves them before unpickling.
    """

    task_id: str  # hex
    job_id: str
    name: str
    func_id: str  # content hash; body in GCS function table
    args_blob: Optional[bytes]
    dependencies: List[Tuple[str, Tuple[str, int]]]  # (oid hex, owner addr)
    num_returns: int
    return_ids: List[str]
    resources: Dict[str, int]  # fixed-point units
    # Large-args path: the serialized (args, kwargs) lives in the shm store
    # under this id instead of args_blob.
    args_object: Optional[str] = None
    # Positions/keys of top-level ObjectRef arguments the executor resolves
    # to values before invoking the function (reference semantics).
    ref_positions: List[int] = field(default_factory=list)
    kw_ref_keys: List[str] = field(default_factory=list)
    max_retries: int = 0
    retry_exceptions: bool = False
    owner_addr: Optional[Tuple[str, int]] = None  # owner's object server
    # Actor fields.
    actor_id: Optional[str] = None
    actor_creation: bool = False
    actor_method: Optional[str] = None
    seq_no: int = -1
    caller_id: Optional[str] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    max_task_retries: int = 0
    # Per-method concurrency groups (reference:
    # transport/concurrency_group_manager.cc): {"group": max_concurrency}.
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: Optional[str] = None
    # Placement.
    pg_id: Optional[str] = None
    bundle_index: int = -1
    scheduling_strategy: Optional[dict] = None
    runtime_env: Optional[dict] = None
    # Named actor registration.
    actor_name: Optional[str] = None
    namespace: Optional[str] = None

    def to_wire(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}

    @classmethod
    def from_wire(cls, d: dict) -> "TaskSpec":
        known = {k: d[k] for k in cls.__dataclass_fields__ if k in d}
        return cls(**known)


@dataclass
class Bundle:
    """One placement-group bundle: a resource reservation on a single node."""

    resources: Dict[str, int]  # fixed-point
    node_id: Optional[str] = None  # filled once placed


@dataclass
class PlacementGroupSpec:
    pg_id: str
    bundles: List[Dict[str, int]]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    job_id: str = ""

    def to_wire(self) -> dict:
        return {
            "pg_id": self.pg_id,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "name": self.name,
            "job_id": self.job_id,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PlacementGroupSpec":
        return cls(**d)


# ---------------------------------------------------------------------------
# Errors (analog of python/ray/exceptions.py).
# ---------------------------------------------------------------------------


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised by user task code; re-raised at ray.get."""

    def __init__(self, cause: BaseException, task_name: str = "", traceback_str: str = ""):
        self.cause = cause
        self.task_name = task_name
        self.traceback_str = traceback_str
        super().__init__(f"task {task_name!r} failed: {cause!r}\n{traceback_str}")


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ActorUnavailableError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    pass


class ObjectReconstructionFailedError(ObjectLostError):
    """A lost object could not be rebuilt from lineage: the producing
    TaskSpec was pruned under lineage_bytes_limit, the producer was a
    ray.put / non-retriable actor task (no lineage exists), or the
    reconstruction recursion exceeded reconstruction_max_depth."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
