"""Raylet: the per-node daemon.

TPU-native analog of the reference's raylet (src/ray/raylet/node_manager.cc):
worker-pool management, lease-based task scheduling with spillback, placement
group bundle 2PC resource accounting, and the node's shared-memory object
store (the plasma-store role: src/ray/object_manager/plasma/store.h — data
lives in one shm arena per node; a native StoreCore manages offsets, sealing,
pinning and LRU eviction; clients map the arena once and read/write at
offsets, zero-copy).

Accelerator detection: reports a ``TPU`` resource per local chip plus the
pod-slice gang resource ``TPU-{pod_type}-head`` on worker 0 of a slice,
mirroring the reference's TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:75,382).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import sys
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private import aiocheck, external_storage, rpc, shm, telemetry
from ray_tpu._private import pull_manager as pull_manager_mod
from ray_tpu._private.pull_manager import PullStalled
from ray_tpu._private.push_manager import PushManager
from ray_tpu._private.common import ResourceSet, adaptive_chunk_size, config
from ray_tpu._private.gcs import GcsClient
from ray_tpu._private.store_core import make_store_core
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

# Lease/worker-pool counters (cells bound per raylet in __init__ so
# in-process multi-raylet clusters attribute correctly) and object-store
# lifecycle counters. Gauges refresh from _tel_refresh_gauges at each
# pool/lease mutation.
_TEL_LEASE_GRANTED = telemetry.counter(
    "raylet", "lease_granted", "worker leases committed (grant ledger entries)"
)
_TEL_LEASE_RELEASED = telemetry.counter(
    "raylet", "lease_released", "leases released (worker returned or killed)"
)
_TEL_LEASE_CANCELLED = telemetry.counter(
    "raylet", "lease_cancelled", "queued lease requests cancelled"
)
_TEL_LEASE_DUPLICATE = telemetry.counter(
    "raylet", "lease_duplicate_avoided",
    "duplicate lease grants answered idempotently via the ledger",
)
_TEL_WORKERS_STARTED = telemetry.counter(
    "raylet", "workers_started", "worker processes spawned"
)
_TEL_WORKERS_EXITED = telemetry.counter(
    "raylet", "workers_exited", "worker processes reaped"
)
_TEL_WORKERS = telemetry.gauge("raylet", "workers", "worker processes attached")
_TEL_WORKERS_IDLE = telemetry.gauge(
    "raylet", "workers_idle", "idle pooled workers"
)
_TEL_LEASES_ACTIVE = telemetry.gauge("raylet", "leases_active", "live leases")
_TEL_LEASE_GRANT_LATENCY = telemetry.histogram(
    "raylet", "lease_grant_latency_s",
    "queue-to-grant latency of worker lease requests",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_LEASE_SPILLBACKS = telemetry.counter(
    "raylet", "lease_spillbacks",
    "lease requests redirected to another node (one per spillback hop)",
)
_TEL_LOCALITY_HITS = telemetry.counter(
    "raylet", "locality_hits",
    "lease requests placed on a node already holding the task's args",
)
_TEL_LOCALITY_MISSES = telemetry.counter(
    "raylet", "locality_misses",
    "lease requests with locality hints placed on a non-hinted node",
)
_TEL_NODE_UTIL = telemetry.gauge(
    "raylet", "node_utilization",
    "max per-resource utilization of this node (0..1)",
)
_TEL_OBJ_SEALED = telemetry.counter(
    "object", "sealed", "objects sealed in the local store"
)
_TEL_OBJ_EVICTED = telemetry.counter(
    "object", "evicted", "sealed objects LRU-evicted under allocation pressure"
)
_TEL_OBJ_SPILLED_BYTES = telemetry.counter(
    "object", "spilled_bytes", "bytes written to external spill storage"
)
_TEL_OBJ_RESTORED_BYTES = telemetry.counter(
    "object", "restored_bytes", "bytes restored from external spill storage"
)
_TEL_SPILL_LATENCY = telemetry.histogram(
    "object", "spill_latency_s", "external-storage write latency per object",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_RESTORE_LATENCY = telemetry.histogram(
    "object", "restore_latency_s", "external-storage read latency per object",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_ARENA_PRESSURE = telemetry.gauge(
    "object", "arena_pressure",
    "shm arena occupancy fraction (used/capacity) seen by the pressure loop",
)


def detect_tpu_resources() -> Dict[str, float]:
    """Probe local accelerators through the pluggable manager registry
    (reference: accelerators/__init__.py + TPUAcceleratorManager tpu.py:75 —
    env overrides, /dev/accel*, /dev/vfio, then GCE/GKE instance metadata
    for the pod slice). Daemons must not grab the chips, so nothing here
    touches the JAX runtime."""
    from ray_tpu._private.accelerators import detect_accelerator_resources

    return detect_accelerator_resources()


class ZygoteProc:
    """Process-like shim for a worker forked by the zygote (the asyncio
    subprocess API surface the raylet uses: pid/returncode/terminate/kill/
    wait + stdout/stderr StreamReaders). Exits arrive as zygote messages;
    wait() also polls the pid so a dead zygote cannot wedge teardown."""

    def __init__(self, pid: int, stdout, stderr):
        self.pid = pid
        self.returncode: Optional[int] = None
        self.stdout = stdout
        self.stderr = stderr
        self._exit_fut: asyncio.Future = asyncio.get_running_loop().create_future()

    def _report_exit(self, code: int) -> None:
        self.returncode = code
        if not self._exit_fut.done():
            self._exit_fut.set_result(code)

    def _signal(self, sig) -> None:
        if self.returncode is not None:
            raise ProcessLookupError(self.pid)
        os.kill(self.pid, sig)

    def terminate(self) -> None:
        import signal as _signal

        self._signal(_signal.SIGTERM)

    def kill(self) -> None:
        import signal as _signal

        self._signal(_signal.SIGKILL)

    async def wait(self) -> int:
        while self.returncode is None:
            try:
                return await asyncio.wait_for(asyncio.shield(self._exit_fut), 0.5)
            except asyncio.TimeoutError:
                try:
                    os.kill(self.pid, 0)
                except ProcessLookupError:
                    # Re-parented to init and reaped there (zygote gone).
                    self._report_exit(-1)
        return self.returncode


class _Zygote:
    """Owns the zygote process + its control socket; serializes fork
    requests (the zygote answers in order)."""

    def __init__(self, raylet: "Raylet"):
        self.raylet = raylet
        self.proc = None
        self.sock = None
        self.reader_task: Optional[asyncio.Task] = None
        self._pending: deque = deque()  # futures awaiting {"forked": pid}
        self._by_pid: Dict[int, ZygoteProc] = {}
        self._lock = asyncio.Lock()
        self.broken = False

    async def start(self, base_env: Dict[str, str]) -> None:
        import socket as _socket

        # Two channels (see worker_zygote.py): requests stay a plain
        # BLOCKING socket owned by us (asyncio must never flip its file
        # description to O_NONBLOCK — a nonblocking sendmsg under a fork
        # burst EAGAINs mid-message and corrupts the protocol); responses
        # are wrapped in an asyncio reader.
        req_ours, req_theirs = _socket.socketpair()
        resp_ours, resp_theirs = _socket.socketpair()
        self.sock = req_ours
        self.proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-m",
            "ray_tpu._private.worker_zygote",
            str(req_theirs.fileno()),
            str(resp_theirs.fileno()),
            env=base_env,
            pass_fds=[req_theirs.fileno(), resp_theirs.fileno()],
        )
        req_theirs.close()
        resp_theirs.close()
        # Keep the writer referenced: StreamWriter.__del__ closes the
        # transport, which would EOF the response channel.
        reader, self._writer = await asyncio.open_connection(sock=resp_ours)
        self.reader_task = rpc.spawn(self._read_loop(reader))

    async def _read_loop(self, reader) -> None:
        import json as _json

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                msg = _json.loads(line)
                if "forked" in msg:
                    if self._pending:
                        fut = self._pending.popleft()
                        if not fut.done():
                            fut.set_result(msg["forked"])
                elif "exit" in msg:
                    proc = self._by_pid.pop(msg["exit"], None)
                    if proc is not None:
                        proc._report_exit(msg.get("code", -1))
        except Exception:
            pass
        finally:
            self.broken = True
            while self._pending:
                fut = self._pending.popleft()
                if not fut.done():
                    fut.set_exception(RuntimeError("zygote died"))

    async def fork_worker(self, env_overrides: Dict[str, str]) -> ZygoteProc:
        from ray_tpu._private.worker_zygote import send_msg

        out_r, out_w = os.pipe()
        err_r, err_w = os.pipe()
        try:
            async with self._lock:
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                self._pending.append(fut)
                try:
                    send_msg(
                        self.sock, {"env": env_overrides}, fds=[out_w, err_w]
                    )
                except BaseException:
                    # A failed/partial send corrupts the request framing and
                    # desynchronizes response matching: poison this zygote
                    # (callers fall back to exec spawn; a fresh zygote is
                    # started lazily) and drop the orphan future so later
                    # responses cannot misroute.
                    self.broken = True
                    try:
                        self._pending.remove(fut)
                    except ValueError:
                        pass
                    raise
            pid = await asyncio.wait_for(
                fut, timeout=config.worker_start_timeout_s
            )
        except BaseException:
            os.close(out_r)
            os.close(err_r)
            raise
        finally:
            os.close(out_w)
            os.close(err_w)
        loop = asyncio.get_running_loop()

        async def fd_reader(fd):
            reader = asyncio.StreamReader()
            protocol = asyncio.StreamReaderProtocol(reader)
            await loop.connect_read_pipe(lambda: protocol, os.fdopen(fd, "rb"))
            return reader

        proc = ZygoteProc(pid, await fd_reader(out_r), await fd_reader(err_r))
        self._by_pid[pid] = proc
        return proc

    async def stop(self) -> None:
        if self.reader_task is not None:
            self.reader_task.cancel()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        if self.proc is not None:
            try:
                self.proc.terminate()
            except ProcessLookupError:
                pass
            try:
                await asyncio.wait_for(self.proc.wait(), 3)
            except asyncio.TimeoutError:
                try:
                    self.proc.kill()
                except ProcessLookupError:
                    pass
                await self.proc.wait()


class _SimWorkerConn:
    """Stand-in worker link for simulated-cluster raylets (sim_workers=True):
    satisfies the liveness checks the grant/duplicate/release paths make
    (closed flag, push_nowait) without a process or socket behind it."""

    __slots__ = ("closed",)

    def __init__(self):
        self.closed = False

    def push_nowait(self, method: str, payload: dict) -> None:
        pass

    def close(self) -> None:
        self.closed = True


class WorkerHandle:
    def __init__(self, worker_id: str, proc=None):
        self.worker_id = worker_id
        self.proc = proc
        self.conn: Optional[rpc.Connection] = None
        self.addr: Optional[Tuple[str, int]] = None
        self.fp_port: Optional[int] = None  # native fastpath channel port
        self.kill_requested = False  # kill arrived while fork in flight
        self.registered = asyncio.get_running_loop().create_future()
        self.lease_id: Optional[str] = None
        self.actor_id: Optional[str] = None
        self.job_id: Optional[str] = None
        self.demand: Optional[ResourceSet] = None
        self.idle_since = time.monotonic()


class LeaseRequest:
    def __init__(self, lease_id: str, demand: ResourceSet, payload: dict):
        self.lease_id = lease_id
        self.demand = demand
        self.payload = payload
        self.queued_at = time.monotonic()  # grant-latency histogram origin
        self.queued_wall = time.time()  # lease-lifecycle span origin
        # Trace context of the requesting frame (set by rpc dispatch around
        # the handler that constructs us). Grant-time spans are emitted long
        # after that dispatch task is gone, so the ctx is pinned here.
        self.trace_ctx = rpc._trace_ctx.get()
        self.grant_started: Optional[float] = None
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()


class _ArenaChunkSink:
    """Blob sink streaming one inbound PushChunk straight into the
    destination arena span. Every write re-validates the assembly: the
    condemned sweep or an abort can free (and something else reallocate)
    the span while the blob is mid-stream, and writing on would corrupt
    whoever reuses it. ``st`` identity is the guard — a fresh assembly for
    the same oid has a different dict."""

    __slots__ = ("raylet", "oid", "st", "pos")

    def __init__(self, raylet, oid: str, st: dict, off: int, size: int):
        self.raylet = raylet
        self.oid = oid
        self.st = st
        self.pos = st["offset"] + off

    def write(self, view) -> None:
        st = self.st
        if st is None:
            return
        r = self.raylet
        if r.push_assembly.get(self.oid) is not st:
            self.st = None  # aborted/superseded mid-blob: drop the rest
            return
        if self.oid in r.condemned:
            del r.push_assembly[self.oid]
            self.st = None
            return
        n = view.nbytes
        r.arena.view[self.pos : self.pos + n] = view
        self.pos += n
        st["recv"] += n
        st["last"] = time.monotonic()

    def done(self, ok: bool) -> None:
        st = self.st
        if st is None or self.raylet.push_assembly.get(self.oid) is not st:
            return
        if not ok:
            # Connection died mid-blob: the span holds a torn chunk.
            self.raylet._abort_push_assembly(self.oid)
            return
        if st["recv"] >= st["size"]:
            del self.raylet.push_assembly[self.oid]
            rpc.spawn(self.raylet._obj_seal(None, {"oid": self.oid}))


class Raylet:
    # Class-level fallbacks (unlabeled cells, placeholder node id) so
    # ledger/pool helpers stay callable on partially-constructed instances
    # (tests build bare Raylets with object.__new__); __init__ rebinds them
    # with the node label.
    node_id = "?"
    _tel_lease_granted = _TEL_LEASE_GRANTED.cell()
    _tel_lease_released = _TEL_LEASE_RELEASED.cell()
    _tel_lease_cancelled = _TEL_LEASE_CANCELLED.cell()
    _tel_lease_duplicate = _TEL_LEASE_DUPLICATE.cell()
    _tel_workers_started = _TEL_WORKERS_STARTED.cell()
    _tel_workers_exited = _TEL_WORKERS_EXITED.cell()
    _tel_workers = _TEL_WORKERS.cell()
    _tel_workers_idle = _TEL_WORKERS_IDLE.cell()
    _tel_leases_active = _TEL_LEASES_ACTIVE.cell()
    _tel_grant_latency = _TEL_LEASE_GRANT_LATENCY.cell()
    _tel_spillbacks = _TEL_LEASE_SPILLBACKS.cell()
    _tel_locality_hits = _TEL_LOCALITY_HITS.cell()
    _tel_locality_misses = _TEL_LOCALITY_MISSES.cell()
    _tel_node_util = _TEL_NODE_UTIL.cell()
    _tel_spilled_bytes = _TEL_OBJ_SPILLED_BYTES.cell()
    _tel_restored_bytes = _TEL_OBJ_RESTORED_BYTES.cell()
    _tel_spill_latency = _TEL_SPILL_LATENCY.cell()
    _tel_restore_latency = _TEL_RESTORE_LATENCY.cell()
    _tel_arena_pressure = _TEL_ARENA_PRESSURE.cell()

    # Mutation gate for the interleaving explorer (devtools/explore.py):
    # when True, both layers of the PR 2 duplicate-grant fix are disabled
    # (the ledger check in _is_duplicate_grant and the leases[] recovery
    # branch in _grant_inner), faithfully re-introducing the double-grant
    # bug so the explorer can prove it still finds it. Never set in
    # production code paths.
    _mutate_double_grant = False

    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        session_name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        node_id: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        worker_env: Optional[Dict[str, str]] = None,
        sim_workers: bool = False,
        gcs_leader_file: Optional[str] = None,
    ):
        from ray_tpu._private.ids import NodeID

        # Simulated-cluster mode: grants attach in-process stub workers
        # instead of forking real worker subprocesses, so hundreds of
        # raylets fit in one process (tests/test_scale.py harness).
        self.sim_workers = sim_workers
        self._sim_worker_seq = 0
        # HA control plane: the leader pointer file this raylet (and its
        # workers, via env) re-resolves before every GCS redial, so a
        # failover re-targets the promoted standby (gcs_ha.py).
        self.gcs_leader_file = gcs_leader_file or config.gcs_leader_file or None

        self.node_id = node_id or NodeID.from_random().hex()
        self.session_name = session_name
        self.gcs_addr = gcs_addr
        self.labels = labels or {}
        self.worker_env = worker_env or {}
        self.server = rpc.Server(host, port)
        self.gcs: Optional[GcsClient] = None

        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
            resources.update(detect_tpu_resources())
        resources.setdefault("node:" + self.node_id[:8], 1.0)
        self.total = ResourceSet(resources)
        self.available = ResourceSet(resources)

        # Object store.
        if object_store_memory is None:
            try:
                import psutil  # type: ignore

                mem = psutil.virtual_memory().total
            except ImportError:
                mem = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            object_store_memory = max(
                config.object_store_memory_min,
                int(mem * config.object_store_memory_fraction),
            )
        self.store_capacity = object_store_memory
        # Arena store: one shm segment per node, offsets managed by the
        # (native) StoreCore — plasma's dlmalloc-over-mmap design. Created in
        # start(); obj_waiters holds futures blocking on unsealed objects,
        # obj_last_access drives the time-grace eviction filter.
        self.store = make_store_core(object_store_memory)
        self.arena_name = f"rt_{self.session_name[:10]}_{self.node_id[:10]}"
        self.arena: Optional[shm.Segment] = None
        self.obj_waiters: Dict[str, List[asyncio.Future]] = {}
        self.obj_last_access: Dict[str, float] = {}
        # Deleted objects are quarantined (not freed) for the grace window:
        # clients may still hold zero-copy views into their arena bytes.
        self.condemned: Dict[str, float] = {}
        # Spilled objects: oid -> (uri, size, pinned). Sealed objects are
        # written out via the pluggable ExternalStorage backend when the arena
        # fills and restored on access (reference: raylet LocalObjectManager
        # spill orchestration + python/ray/_private/external_storage.py).
        # Spill/restore IO runs on a thread pool, never on the event loop
        # (reference spills via async IO workers, local_object_manager.cc) —
        # `spilling` tracks in-flight writes (bytes still live in the arena
        # until the write lands), `restoring` coalesces concurrent reads.
        self.spilled: Dict[str, Tuple[str, int, bool]] = {}
        self.spilled_bytes = 0
        self.spilling: Dict[str, asyncio.Task] = {}
        self.restoring: Dict[str, asyncio.Future] = {}
        # Owner-pinned primary copies (PinObject): the spill scheduler and
        # LRU eviction never touch these, whatever the pressure — an owner
        # that pins is promising to unpin or delete.
        self.pinned_objects: set = set()
        base = config.object_spilling_dir or os.path.join(
            "/tmp", "ray_tpu_spill"
        )
        spill_ns = f"{self.session_name[:16]}_{self.node_id[:8]}"
        self.spill_dir = os.path.join(base, spill_ns)
        self.storage = external_storage.create_storage(
            config.object_spilling_config, self.spill_dir, namespace=spill_ns
        )
        self._io_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, config.max_io_workers),
            thread_name_prefix=f"spill-io-{self.node_id[:6]}",
        )
        # Cross-node transfer: source-side push fan-out with a global chunk
        # budget (reference: push_manager.h); `push_assembly` tracks inbound
        # pushes being written into unsealed spans.
        self.push_manager = PushManager(self)
        # Inbound transfer admission (reference: pull_manager.h prioritized,
        # bandwidth-capped pulls).
        from ray_tpu._private.pull_manager import PullManager

        self.pull_manager = PullManager(
            config.pull_max_bytes_in_flight,
            stall_timeout_s=config.pull_stall_timeout_s,
            max_rerequests=config.pull_max_rerequests,
        )
        # Preloaded fork server for fast worker spawn (reference:
        # worker_pool.cc prestart); started lazily on first spawn.
        self._zygote: Optional[_Zygote] = None
        self.push_assembly: Dict[str, Dict[str, int]] = {}
        # Per-worker stdout/stderr files (reference: session_latest/logs).
        import tempfile

        self.log_dir = os.path.join(
            tempfile.gettempdir(),
            f"ray_tpu_{self.session_name}",
            "logs",
            self.node_id[:8],
        )
        # Client holds (plasma's per-client buffer refcounts,
        # plasma/client.h): ObjGet increments for the calling connection,
        # ObjRelease decrements, disconnect clears. Held objects are never
        # freed/evicted, whatever their age.
        self.obj_holds: Dict[str, Dict[int, int]] = {}

        # Workers. Shared single-loop state mutated from many handlers;
        # aiocheck.track attributes mutations to asyncio tasks under
        # RAY_TPU_AIOCHECK=1 (no-op otherwise).
        self.workers: Dict[str, WorkerHandle] = aiocheck.track("raylet.workers")
        self.idle_workers: List[WorkerHandle] = []
        self.pending_leases: List[LeaseRequest] = []
        # Cluster-wide-infeasible leases parked off the FIFO grant queue
        # until the cluster scales (autoscaler demand input).
        self.infeasible_leases: List[LeaseRequest] = []
        self.leases: Dict[str, WorkerHandle] = aiocheck.track("raylet.leases")
        # Exactly-once grant ledger: every lease id this raylet has COMMITTED
        # to granting (recorded synchronously with the resource deduction,
        # before the async _grant task runs). A duplicated RequestWorkerLease
        # frame (retry, wire-level duplication — reproduced by the
        # RAY_TPU_AIOCHECK probe as a cross-task write-write on raylet.leases)
        # queues the same lease id twice; without the ledger the second grant
        # overwrites the first's leases[] entry and leaks that worker +
        # its resources forever. Bounded LRU: ids only need to outlive the
        # duplicate-arrival window, not the session.
        self.granted_lease_ids: "OrderedDict[str, bool]" = OrderedDict()
        # Actor lease ids whose grant+CreateActor is currently in flight:
        # distinguishes a wire-duplicated placement (mirror the original)
        # from a GCS re-placement of a completed lease (supersede it).
        self.actor_creations_in_flight: set = set()
        self.duplicate_lease_grants_avoided = 0
        # Grants spawned but not yet resolved: their resources are deducted
        # but the lease is not in `leases` yet, so ledger observers must
        # treat the node as busy while this is nonzero.
        self.grants_in_flight = 0

        # Telemetry cells bound to this raylet (in-process clusters run
        # several raylets in one registry; the label keeps them apart).
        _nid = self.node_id[:8]
        self._tel_lease_granted = _TEL_LEASE_GRANTED.cell(raylet=_nid)
        self._tel_lease_released = _TEL_LEASE_RELEASED.cell(raylet=_nid)
        self._tel_lease_cancelled = _TEL_LEASE_CANCELLED.cell(raylet=_nid)
        self._tel_lease_duplicate = _TEL_LEASE_DUPLICATE.cell(raylet=_nid)
        self._tel_workers_started = _TEL_WORKERS_STARTED.cell(raylet=_nid)
        self._tel_workers_exited = _TEL_WORKERS_EXITED.cell(raylet=_nid)
        self._tel_workers = _TEL_WORKERS.cell(raylet=_nid)
        self._tel_workers_idle = _TEL_WORKERS_IDLE.cell(raylet=_nid)
        self._tel_leases_active = _TEL_LEASES_ACTIVE.cell(raylet=_nid)
        self._tel_grant_latency = _TEL_LEASE_GRANT_LATENCY.cell(raylet=_nid)
        self._tel_spillbacks = _TEL_LEASE_SPILLBACKS.cell(raylet=_nid)
        self._tel_locality_hits = _TEL_LOCALITY_HITS.cell(raylet=_nid)
        self._tel_locality_misses = _TEL_LOCALITY_MISSES.cell(raylet=_nid)
        self._tel_node_util = _TEL_NODE_UTIL.cell(raylet=_nid)
        self._tel_spilled_bytes = _TEL_OBJ_SPILLED_BYTES.cell(raylet=_nid)
        self._tel_restored_bytes = _TEL_OBJ_RESTORED_BYTES.cell(raylet=_nid)
        self._tel_spill_latency = _TEL_SPILL_LATENCY.cell(raylet=_nid)
        self._tel_restore_latency = _TEL_RESTORE_LATENCY.cell(raylet=_nid)
        self._tel_arena_pressure = _TEL_ARENA_PRESSURE.cell(raylet=_nid)

        # Placement group bundles committed on this node:
        # pg_id -> {"base": ResourceSet deducted, "group": ResourceSet added}
        self.pg_prepared: Dict[str, ResourceSet] = {}
        self.pg_committed: Dict[str, Tuple[ResourceSet, ResourceSet]] = {}

        self._resources_dirty = asyncio.Event()
        # Full cluster view: pull-based with a ~1s TTL, consumed only by
        # cold paths (node affinity, label pick, locality hints beyond the
        # head, spillback fallback). The per-lease hot path never walks it.
        self._view: List[dict] = []
        self._view_time = 0.0
        self._view_map: Dict[str, dict] = {}
        self._view_addr: Dict[str, str] = {}  # "host:port" -> node_id
        self._view_fetched_epoch = -1
        self._view_fetch = None
        # Scheduling head (reference: ray_syncer.h:88, inverted): the GCS —
        # the one process that sees every resource report — keeps the
        # utilization-sorted order and broadcasts only the sorted head, so
        # a flush costs each subscriber O(head cap) instead of O(changed
        # nodes), and the per-lease pick walks the head: O(k), never
        # O(cluster). Each message replaces the previous head wholesale.
        self._head: List[dict] = []  # {node_id, addr, total, available, util}
        self._head_addr_map: Optional[Dict[str, dict]] = None  # lazy
        self._head_n = 0  # alive-node count cluster-wide
        self._head_version = -1
        # GCS shape epoch: bumped on membership/total-capacity change; keys
        # the SPREAD ring cache (ring membership only depends on totals, not
        # availability) and forces a full-view refetch when it moves.
        self._head_epoch = -1
        self._spread_rr = 0
        self._spread_ring: Optional[Tuple[int, tuple, list]] = None
        # Monotonic version on our own resource reports so the GCS can drop
        # stale/out-of-order updates.
        self._report_version = 0
        self._tasks: List[asyncio.Task] = []
        self._register_handlers()

    @property
    def store_used(self) -> int:
        return self.store.used

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> Tuple[str, int]:
        self.arena = shm.create(self.arena_name, self.store_capacity)
        if config.prefault_object_store:
            # Touch every arena page off the event loop so large-object puts
            # don't pay first-touch page faults (plasma_allocator.cc analog).
            import threading

            def _prefault(view=self.arena.view):
                try:
                    from ray_tpu._native import _shm as native_shm

                    native_shm.prefault(view, 4)
                except Exception:
                    try:
                        for off in range(0, len(view), 4096):
                            view[off] = view[off]
                    except Exception:
                        pass

            threading.Thread(target=_prefault, name="arena_prefault", daemon=True).start()
        addr = await self.server.start()
        self.server.on_disconnect(self._on_disconnect)
        # Duplex: the GCS calls back over this link (LeaseWorkerForActor,
        # KillWorker, PG prepare/commit), so expose our handlers on it.
        conn = await rpc.connect(*self.gcs_addr, handlers=self.server._handlers)
        resolver = None
        if self.gcs_leader_file:
            from ray_tpu._private import gcs_ha

            resolver = gcs_ha.file_resolver(self.gcs_leader_file)
        self.gcs = GcsClient(conn, resolver=resolver)
        self.addr = addr

        async def _register(client) -> None:
            # Initial registration AND post-GCS-restart re-registration
            # (reference: raylet side of NotifyGCSRestart,
            # node_manager.proto:373): a restarted GCS has no node table
            # until every raylet re-announces itself.
            payload = {
                "node_id": self.node_id,
                "addr": list(self.addr),
                "resources": self.total.to_units(),
                "labels": self.labels,
            }
            # Lease-picture rebuild: report the actor workers this node is
            # hosting so a restarted GCS confirms its restored-ALIVE actors
            # from re-registrations instead of probing each one.
            actors = [
                {"actor_id": h.actor_id, "worker_id": h.worker_id}
                for h in self.workers.values()
                if h.actor_id is not None
            ]
            if actors:
                payload["actors"] = actors
            await client.conn.call("RegisterNode", payload)
            # A restarted GCS numbers heads from zero: drop the stale head
            # and view so the next broadcast/pick resyncs from scratch.
            self._head_version = -1
            self._view_time = 0.0
            self._mark_dirty()

        self.gcs.on_reconnect(_register)
        await _register(self.gcs)
        await self.gcs.subscribe("syncer:nodes", self._on_view_head)
        self._tasks.append(rpc.spawn(self._resource_report_loop()))
        self._tasks.append(rpc.spawn(self._condemned_sweep_loop()))
        self._tasks.append(rpc.spawn(self._infeasible_retry_loop()))
        if config.memory_monitor_interval_s > 0:
            self._tasks.append(rpc.spawn(self._memory_monitor_loop()))
        if config.object_spilling_threshold > 0:
            self._tasks.append(rpc.spawn(self._pressure_loop()))
        logger.info(
            "raylet %s on %s:%s resources=%s",
            self.node_id[:8],
            addr[0],
            addr[1],
            self.total.to_dict(),
        )
        return addr

    async def stop(self) -> None:
        if self.gcs is not None:
            # Graceful departure: tell the GCS this node is leaving so the
            # dropped link is not reported as a health-check death.
            try:
                await asyncio.wait_for(
                    self.gcs.call("UnregisterNode", {"node_id": self.node_id}),
                    2,
                )
            except Exception:
                pass
            await self.gcs.close()  # before anything else: no re-registration
        for t in self._tasks:
            t.cancel()
        # Fail queued lease futures so their handler frames unwind now:
        # callers get a retryable error (or already saw the link drop) and
        # in-process harnesses (sim_cluster, chaos kill_raylet) don't
        # accumulate orphaned handler tasks until their loop closes.
        for req in self.pending_leases + self.infeasible_leases:
            if not req.fut.done():
                req.fut.set_exception(rpc.RpcError("raylet stopping"))
        self.pending_leases.clear()
        self.infeasible_leases.clear()
        procs = [w.proc for w in list(self.workers.values()) if w.proc is not None]
        for w in list(self.workers.values()):
            # Graceful first: the worker's Exit handler flushes and exits 0;
            # SIGTERM right behind it is the backstop for a wedged loop.
            if w.conn is not None and not w.conn.closed:
                try:
                    w.conn.push_nowait("Exit", {})
                except rpc.ConnectionLost:
                    pass
            self._kill_worker_proc(w)
        # Reap children through the event loop so their subprocess
        # transports close while the loop is alive — otherwise transport
        # __del__ at interpreter exit emits "child process exit status
        # already read" / "Event loop is closed" noise.
        if procs:
            try:
                await asyncio.wait_for(
                    asyncio.gather(*(p.wait() for p in procs), return_exceptions=True),
                    5,
                )
            except asyncio.TimeoutError:
                for p in procs:
                    try:
                        p.kill()
                    except ProcessLookupError:
                        pass
                await asyncio.gather(
                    *(p.wait() for p in procs), return_exceptions=True
                )
        if self._zygote is not None:
            try:
                await self._zygote.stop()
            except Exception:
                pass
            self._zygote = None
        # Quiesce spill IO before the arena unmaps: pool threads and
        # suspended spill/restore frames hold memoryview slices into it;
        # mmap.close() with exported views raises BufferError.
        spill_tasks = list(self.spilling.values())
        for t in spill_tasks:
            t.cancel()
        if spill_tasks:
            await asyncio.gather(*spill_tasks, return_exceptions=True)
        self.spilling.clear()
        # Delete each remaining spill file individually BEFORE destroy():
        # destroy() is a backstop (rmtree / delete_dir_contents) that some
        # backends implement partially or not at all, and a session sharing
        # an external bucket must not leak its per-object keys. The deletes
        # ride the IO pool; the bounded shutdown below drains them.
        del_futs = []
        for uri, _size, _pinned in self.spilled.values():
            try:
                del_futs.append(self._io_pool.submit(self.storage.delete, uri))
            except RuntimeError:
                break
        self.spilled.clear()
        self.spilled_bytes = 0
        self.pinned_objects.clear()
        if del_futs:
            try:
                await asyncio.wait_for(
                    asyncio.get_running_loop().run_in_executor(
                        None,
                        lambda: concurrent.futures.wait(
                            del_futs, timeout=config.io_pool_shutdown_timeout_s
                        ),
                    ),
                    timeout=config.io_pool_shutdown_timeout_s + 1,
                )
            except (asyncio.TimeoutError, RuntimeError):
                pass
        try:
            # Bounded: a wedged storage backend (stalled NFS/remote store)
            # must not hang node shutdown; the arena-close retry below copes
            # if a thread is abandoned mid-IO.
            await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(
                    None,
                    lambda: self._io_pool.shutdown(wait=True, cancel_futures=True),
                ),
                timeout=config.io_pool_shutdown_timeout_s,
            )
        except (asyncio.TimeoutError, RuntimeError):
            logger.warning("spill IO pool did not quiesce; abandoning threads")
        for fut in list(self.restoring.values()):
            try:
                await asyncio.wait_for(asyncio.shield(fut), timeout=5)
            except Exception:
                pass
        try:
            self.storage.destroy()
        except Exception:
            pass
        self.push_assembly.clear()
        await self.push_manager.close()
        if self.arena is not None:
            for _ in range(100):
                try:
                    self.arena.close()
                    break
                except BufferError:
                    # An RPC handler frame still holds a view; it releases
                    # within a loop turn or two.
                    await asyncio.sleep(0.05)
            try:
                shm.unlink(self.arena_name)
            except Exception:
                pass
        await self.server.stop()
        if self.gcs is not None:
            await self.gcs.conn.close()

    def _register_handlers(self) -> None:
        s = self.server
        s.register("RegisterWorker", self._register_worker)
        s.register("RequestWorkerLease", self._request_worker_lease)
        s.register("CancelWorkerLease", self._cancel_worker_lease)
        s.register("ReturnWorker", self._return_worker)
        # Lease fast path: the unconstrained-grant/release/cancel cases run
        # inline from the read loop (no dispatch task, no deadline wrapper);
        # anything they can't settle synchronously falls through to the
        # async handlers registered above.
        s.register_sync("RequestWorkerLease", self._request_worker_lease_sync)
        s.register_sync("ReturnWorker", self._return_worker_sync)
        s.register_sync("CancelWorkerLease", self._cancel_worker_lease_sync)
        s.register("LeaseWorkerForActor", self._lease_worker_for_actor)
        s.register("KillWorker", self._kill_worker)
        s.register("ObjCreate", self._obj_create)
        s.register("ObjSeal", self._obj_seal)
        s.register("ObjGet", self._obj_get)
        s.register("ObjRelease", self._obj_release)
        s.register("ObjDelete", self._obj_delete)
        s.register("ObjContains", self._obj_contains)
        s.register("PullObject", self._pull_object)
        s.register("FetchChunk", self._fetch_chunk)
        s.register("SpillObjects", self._spill_objects)
        s.register("RestoreSpilled", self._restore_spilled)
        s.register("PinObject", self._pin_object)
        s.register("PushObject", self._push_object)
        s.register("PushStart", self._push_start)
        s.register_blob("PushChunk", self._push_chunk_sink)
        s.register("PreparePGBundles", self._prepare_pg)
        s.register("CommitPGBundles", self._commit_pg)
        s.register("ReleasePGBundles", self._release_pg)
        s.register("GetNodeStats", self._node_stats)
        s.register("GetLog", self._get_log)
        s.register("ListLogs", self._list_logs)
        s.register("Ping", self._ping)

    async def _ping(self, conn, p):
        return {"pong": True, "node_id": self.node_id}

    async def _list_logs(self, conn, p):
        """Log files captured on this node (reference: state API list_logs)."""
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            names = []
        return {"node_id": self.node_id, "files": names}

    async def _get_log(self, conn, p):
        """Tail of one captured log (reference: state API get_log,
        python/ray/util/state/api.py:1183). Accepts a filename from
        ListLogs or a worker_id (+ stream)."""
        filename = p.get("filename")
        if filename is None and p.get("worker_id"):
            filename = os.path.basename(
                self._log_path(p["worker_id"], p.get("stream", "stderr"))
            )
        if filename is None or "/" in filename or ".." in filename:
            raise rpc.RpcError("GetLog needs a valid filename or worker_id")
        path = os.path.join(self.log_dir, filename)
        tail = int(p.get("tail") or 1000)

        def _read_tail() -> bytes:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max(tail, 1) * 200))
                return f.read()

        try:
            # Log files can be large and live on slow disks; don't stall the
            # scheduler loop on the read.
            data = await asyncio.get_running_loop().run_in_executor(
                None, _read_tail
            )
        except OSError:
            return {"lines": [], "found": False}
        lines = data.decode("utf-8", "replace").splitlines()
        return {"lines": lines[-tail:], "found": True}

    # -- resource reporting --------------------------------------------------

    async def _resource_report_loop(self) -> None:
        debounce = config.raylet_report_debounce_s
        while True:
            # Hot path: under grant/release churn the dirty event is almost
            # always already set when we come back around — skip the
            # wait_for (a timer + waiter task per iteration, pure loop
            # churn) and optionally debounce so a burst of mutations folds
            # into one UpdateResources round-trip instead of one each.
            if not self._resources_dirty.is_set():
                # Park on the dirty event with a call_later heartbeat that
                # force-sets it after 1s — same "report at least every
                # second" behavior as wait_for(..., 1.0) without the wrapper
                # task wait_for creates per iteration (one extra task per
                # report at cluster scale).
                hb = asyncio.get_running_loop().call_later(
                    1.0, self._resources_dirty.set
                )
                try:
                    await self._resources_dirty.wait()
                finally:
                    hb.cancel()
            if debounce > 0 and self._resources_dirty.is_set():
                # Debounce on the wakeup path too: a lease cycle dirties the
                # ledger twice (grant, then release milliseconds later) —
                # reporting immediately on the first wake would send two
                # UpdateResources per lease where one suffices.
                await asyncio.sleep(debounce)
            self._resources_dirty.clear()
            self._tel_node_util.set(self._local_util())
            self._report_version += 1
            payload = {
                "node_id": self.node_id,
                "available": self.available.to_units(),
                "total": self.total.to_units(),
                "version": self._report_version,
            }
            # Steady state: reports ride as pushes — no reply frame, no
            # caller future, no timeout timer (the reference syncer's
            # ack-free stream). Safe because each report is the FULL
            # versioned resource state: a lost push is superseded by the
            # next report or the 1s idle heartbeat, and the GCS drops
            # out-of-order versions. Only when the link is down do we fall
            # back to gcs.call, whose retry machinery redials.
            try:
                conn = self.gcs.conn
                if conn is not None and not conn.closed:
                    conn.push_nowait("UpdateResources", payload)
                    continue
            except rpc.ConnectionLost:
                pass
            try:
                await self.gcs.call("UpdateResources", payload)
            except rpc.RpcError:
                logger.warning("gcs unreachable from raylet %s", self.node_id[:8])
                await asyncio.sleep(1.0)

    def _mark_dirty(self) -> None:
        # The node-util gauge refreshes in the report loop (once per
        # debounced report), not here: grant/release each mark dirty and
        # recomputing the max-ratio scan twice per lease is avoidable work
        # on the hot path.
        self._resources_dirty.set()

    # -- worker pool ---------------------------------------------------------

    async def _start_worker(self, container: Optional[dict] = None) -> WorkerHandle:
        from ray_tpu._private.ids import WorkerID

        worker_id = WorkerID.from_random().hex()
        env = dict(os.environ)
        # Ensure workers can import ray_tpu regardless of the driver's cwd.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else "")
            )
        # Vars listed in RAY_TPU_WORKER_ENV_DROP are removed from worker
        # environments (e.g. the axon sitecustomize trigger, whose jax
        # import costs ~2s per worker spawn that CPU-only suites never use).
        for name in (env.get("RAY_TPU_WORKER_ENV_DROP") or "").split(","):
            if name:
                env.pop(name, None)
        env.update(self.worker_env)
        env.update(
            {
                "RAY_TPU_RAYLET_HOST": self.server.address[0],
                "RAY_TPU_RAYLET_PORT": str(self.server.address[1]),
                "RAY_TPU_GCS_HOST": self.gcs_addr[0],
                "RAY_TPU_GCS_PORT": str(self.gcs_addr[1]),
                "RAY_TPU_NODE_ID": self.node_id,
                "RAY_TPU_WORKER_ID": worker_id,
                "RAY_TPU_SESSION": self.session_name,
            }
        )
        if self.gcs_leader_file:
            env["RAY_TPU_GCS_LEADER_FILE"] = self.gcs_leader_file
        proc = None
        if container:
            # Containerized worker (reference: runtime_env/container.py):
            # the podman/docker argv wraps the same worker module; host
            # networking + /dev/shm keep RPC and plasma working.
            from ray_tpu.runtime_env.container import build_container_argv

            argv = build_container_argv(
                container, [sys.executable, "-m", "ray_tpu._private.worker_main"], env
            )
        elif config.worker_zygote_enabled:
            # Fork from the preloaded zygote (~10ms) instead of a cold exec
            # (~0.5-1.5s); fall back to exec if the zygote is broken.
            # The handle must be in self.workers BEFORE the fork: a forked
            # worker can connect and register faster than this coroutine
            # resumes, and _register_worker rejects unknown ids.
            handle = WorkerHandle(worker_id, None)
            self.workers[worker_id] = handle
            try:
                proc = await self._zygote_fork(env)
            except Exception as e:
                logger.warning("zygote fork failed (%r); exec fallback", e)
                proc = None
                del self.workers[worker_id]
            argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        else:
            argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        if proc is None:
            proc = await asyncio.create_subprocess_exec(
                *argv,
                env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
        handle = self.workers.get(worker_id) or WorkerHandle(worker_id, None)
        handle.proc = proc
        self.workers[worker_id] = handle
        self._tel_workers_started.inc()
        telemetry.record_event(
            "raylet", "worker_started", worker_id=worker_id, node=self.node_id[:8]
        )
        self._tel_refresh_gauges()
        if handle.kill_requested:
            self._kill_worker_proc(handle)
        # Log pipeline (reference: log_monitor.py tailing session/logs/*):
        # worker output goes to per-worker session log files AND streams to
        # the driver via GCS pubsub.
        # Per-worker infrastructure tasks. The log pumps never touch
        # ledger/2PC state (a crashed pump loses log lines, nothing else);
        # the reaper IS the supervisor — worker exit drives the lease-ledger
        # repair in _handle_worker_exit, and there is no one to supervise
        # the supervisor.
        rpc.spawn(self._pump_worker_logs(handle, proc.stdout, "stdout"))  # rpc-flow: disable=unsupervised-spawn
        rpc.spawn(self._pump_worker_logs(handle, proc.stderr, "stderr"))  # rpc-flow: disable=unsupervised-spawn
        rpc.spawn(self._reap_worker(handle))  # rpc-flow: disable=unsupervised-spawn
        return handle

    async def _zygote_fork(self, env: Dict[str, str]) -> ZygoteProc:
        """Fork one worker from the (lazily started) zygote. env is the
        full worker environment; the base snapshot rides the zygote's own
        spawn, the per-worker delta rides the fork request."""
        z = self._zygote
        if z is None or z.broken:
            z = self._zygote = _Zygote(self)
            await z.start(env)
        overrides = {
            k: v
            for k, v in env.items()
            if k.startswith("RAY_TPU_") or k not in os.environ
        }
        return await z.fork_worker(overrides)

    def _log_path(self, worker_id: str, stream: str) -> str:
        return os.path.join(
            self.log_dir, f"worker-{worker_id[:12]}.{'out' if stream == 'stdout' else 'err'}"
        )

    async def _pump_worker_logs(self, handle: WorkerHandle, pipe, stream: str) -> None:
        """Tail one worker pipe: append to the session log file, batch lines
        to the GCS ``logs`` pubsub channel (driver-side echo). Reference:
        python/ray/_private/log_monitor.py + worker stdout redirection."""
        os.makedirs(self.log_dir, exist_ok=True)
        path = self._log_path(handle.worker_id, stream)
        buf: List[str] = []
        last_flush = 0.0

        async def flush():
            nonlocal buf, last_flush
            if not buf or self.gcs is None:
                buf = []
                return
            lines, buf = buf, []
            last_flush = time.monotonic()
            try:
                await self.gcs.call(
                    "Publish",
                    {
                        "channel": "logs",
                        "msg": {
                            "worker_id": handle.worker_id,
                            "node_id": self.node_id,
                            "pid": handle.proc.pid,
                            "stream": stream,
                            "lines": lines,
                            "actor_id": handle.actor_id,
                            # Job attribution: known for actor workers (the
                            # creation spec carries job_id); pooled task
                            # workers serve whatever job leases them, so
                            # their lines are unattributed.
                            "job_id": handle.job_id,
                        },
                    },
                )
            except rpc.RpcError:
                pass

        carry = b""
        try:
            # Unbuffered append of already-read chunks to a local log file:
            # O(chunk) writes, and per-chunk executor hops would reorder the
            # pump. Accepted sync I/O.
            with open(path, "ab", buffering=0) as f:  # aio-lint: disable=blocking-call
                while True:
                    # Chunked read (not readline): immune to asyncio's 64 KiB
                    # line limit — a worker print()ing a huge repr must never
                    # kill the pump (an undrained pipe wedges the worker).
                    try:
                        chunk = await asyncio.wait_for(pipe.read(65536), timeout=0.5)
                    except asyncio.TimeoutError:
                        if buf and time.monotonic() - last_flush > 0.2:
                            await flush()
                        continue
                    if not chunk:
                        break
                    f.write(chunk)
                    carry += chunk
                    if len(carry) > (1 << 20):
                        # Pathological single line: ship it in pieces.
                        buf.append(carry.decode("utf-8", "replace"))
                        carry = b""
                    elif b"\n" in carry:
                        *lines, carry = carry.split(b"\n")
                        buf.extend(ln.decode("utf-8", "replace") for ln in lines)
                    if buf and (
                        len(buf) >= 100 or time.monotonic() - last_flush > 0.2
                    ):
                        await flush()
        except (OSError, ValueError, asyncio.CancelledError):
            pass
        finally:
            if carry:
                buf.append(carry.decode("utf-8", "replace"))
            await flush()

    async def _reap_worker(self, handle: WorkerHandle) -> None:
        await handle.proc.wait()
        self._handle_worker_exit(handle, f"exit code {handle.proc.returncode}")

    def _handle_worker_exit(self, handle: WorkerHandle, cause: str) -> None:
        if handle.worker_id not in self.workers:
            return
        del self.workers[handle.worker_id]
        if handle in self.idle_workers:
            self.idle_workers.remove(handle)
        self._tel_workers_exited.inc()
        telemetry.record_event(
            "raylet",
            "worker_exit",
            worker_id=handle.worker_id,
            node=self.node_id[:8],
            cause=cause,
        )
        self._tel_refresh_gauges()
        if handle.lease_id and handle.lease_id in self.leases:
            del self.leases[handle.lease_id]
            self._mark_lease_released(handle.lease_id)
            self._free_lease_resources(handle)
        if not handle.registered.done():
            handle.registered.set_exception(rpc.RpcError(f"worker died: {cause}"))
        if handle.actor_id:
            # _report_worker_death retries internally and the GCS also
            # learns of the death from the dropped worker connection —
            # ledger repair already happened above, synchronously.
            rpc.spawn(  # rpc-flow: disable=unsupervised-spawn
                self._report_worker_death(handle.worker_id, [handle.actor_id], cause)
            )

    async def _report_worker_death(self, worker_id, actor_ids, cause) -> None:
        try:
            await self.gcs.call(
                "ReportWorkerDied",
                {"worker_id": worker_id, "actor_ids": actor_ids, "cause": cause},
            )
        except rpc.RpcError:
            pass

    async def _register_worker(self, conn, p):
        handle = self.workers.get(p["worker_id"])
        if handle is None:
            raise rpc.RpcError("unknown worker")
        handle.conn = conn
        handle.addr = tuple(p["addr"])
        handle.fp_port = p.get("fp_port")
        conn.context["worker_id"] = p["worker_id"]
        if not handle.registered.done():
            handle.registered.set_result(handle)
        return {
            "node_id": self.node_id,
            "session_name": self.session_name,
            "gcs_addr": list(self.gcs_addr),
        }

    def _on_disconnect(self, conn: rpc.Connection) -> None:
        cid = id(conn)
        for oid, holds in list(self.obj_holds.items()):
            holds.pop(cid, None)
            if not holds:
                del self.obj_holds[oid]
        # Abort inbound pushes whose source link died: a half-assembled
        # unsealed span would otherwise stay unfetchable forever.
        for oid, st in list(self.push_assembly.items()):
            if st.get("conn") == cid:
                self._abort_push_assembly(oid)
        worker_id = conn.context.get("worker_id")
        if worker_id and worker_id in self.workers:
            handle = self.workers[worker_id]
            # Process may still be flushing; reaper handles true exit. If the
            # RPC link dropped but the process lives, kill it — a worker
            # without its raylet link is unmanageable.
            self._kill_worker_proc(handle)

    def _make_sim_worker(self) -> WorkerHandle:
        self._sim_worker_seq += 1
        wid = f"simw-{self.node_id[:8]}-{self._sim_worker_seq}"
        handle = WorkerHandle(wid)
        handle.sim = True  # type: ignore[attr-defined]
        handle.conn = _SimWorkerConn()  # type: ignore[assignment]
        handle.addr = tuple(self.addr)
        handle.registered.set_result(True)
        self.workers[wid] = handle
        self._tel_workers_started.inc()
        self._tel_refresh_gauges()
        return handle

    def _kill_worker_proc(self, handle: WorkerHandle) -> None:
        if getattr(handle, "sim", False):
            # Simulated worker: no process to reap — finalize synchronously
            # (conn closed + popped from the pool) so the exactly-once
            # invariants see the same end state a real exit produces.
            if handle.conn is not None:
                handle.conn.close()
            if self.workers.pop(handle.worker_id, None) is not None:
                self._tel_workers_exited.inc()
            self._tel_refresh_gauges()
            return
        if handle.proc is None:
            # Fork still in flight: remember the kill; _start_worker
            # delivers it the moment the pid is known.
            handle.kill_requested = True
            return
        try:
            handle.proc.terminate()
        except ProcessLookupError:
            pass

    async def _get_or_start_idle_worker(self) -> WorkerHandle:
        while self.idle_workers:
            handle = self.idle_workers.pop()
            if handle.worker_id in self.workers and handle.conn and not handle.conn.closed:
                return handle
        if self.sim_workers:
            return self._make_sim_worker()
        handle = await self._start_worker()
        await handle.registered
        return handle

    # -- leases --------------------------------------------------------------

    def _translate_pg_demand(self, demand: ResourceSet, pg_id, bundle_index) -> ResourceSet:
        """Rewrite resource names to the PG-scoped resources committed on this
        node (reference encodes bundles as CPU_group_<idx>_<pgid> custom
        resources; see placement_group_resource_manager.cc)."""
        if not pg_id:
            return demand
        units = {}
        for k, v in demand.to_units().items():
            if bundle_index is not None and bundle_index >= 0:
                units[f"{k}_group_{bundle_index}_{pg_id}"] = v
            else:
                units[f"{k}_group_{pg_id}"] = v
        # Gang membership marker so the lease only matches nodes w/ the PG.
        units[f"bundle_group_{pg_id}"] = 1
        return ResourceSet.from_units(units)

    # -- lease fast path (sync handlers, no dispatch task) -------------------

    def _lease_slow_path(self, conn, msgid, method: str, p: dict) -> None:
        """Hand a lease request the sync fast path cannot settle to the
        registered async handler, in its own dispatch task — exactly what
        rpc._on_message would have done had no sync handler existed. The
        ambient deadline/trace the sync dispatch established are re-read
        here and threaded through, so budgets and spans are unchanged."""
        rpc.spawn(  # rpc-flow: disable=unsupervised-spawn
            conn._dispatch(
                msgid, method, p, rpc.current_deadline(), rpc.current_trace_ctx()
            )
        )

    def _request_worker_lease_sync(self, conn, msgid, p) -> None:
        """Inline grant: the common case — an unconstrained lease that fits
        local resources with an idle (or sim) worker on hand and an empty
        queue — commits and replies without creating a single task. The
        semantic fast-path conditions mirror the async handler's
        local-grant route bit for bit: no strategy/locality/PG/spillback
        input (so no policy decision), hybrid policy would stay local
        (fits + util at or below the spread threshold), FIFO preserved
        (pending queue empty), no duplicate ledger hit, and no trace
        context (traced requests take the slow path so lease-lifecycle
        spans keep their exact shape). Everything else falls through to
        the async handler unchanged."""
        if (
            self.pending_leases
            or p.get("strategy")
            or p.get("locality")
            or p.get("spilled_from")
            or p.get("pg_id")
            or self._is_duplicate_grant(p["lease_id"])
            or rpc.current_trace_ctx() is not None
        ):
            self._lease_slow_path(conn, msgid, "RequestWorkerLease", p)
            return
        demand = ResourceSet.from_units(p.get("resources") or {})
        if not (
            demand.is_subset_of(self.available)
            and self._local_util() <= config.scheduler_spread_threshold
        ):
            self._lease_slow_path(conn, msgid, "RequestWorkerLease", p)
            return
        handle = None
        while self.idle_workers:
            h = self.idle_workers.pop()
            if h.worker_id in self.workers and h.conn and not h.conn.closed:
                handle = h
                break
        if handle is None:
            if self.sim_workers:
                handle = self._make_sim_worker()
            else:
                # Would need to spawn a worker process: async territory.
                self._lease_slow_path(conn, msgid, "RequestWorkerLease", p)
                return
        lease_id = p["lease_id"]
        self.available = self.available - demand
        self._mark_dirty()
        self._record_granted(lease_id)
        handle.lease_id = lease_id
        handle.demand = demand  # type: ignore[attr-defined]
        handle.leased_since = time.monotonic()  # type: ignore[attr-defined]
        handle.job_id = p.get("job_id") or handle.job_id
        self.leases[lease_id] = handle
        self._tel_refresh_gauges()
        self._tel_grant_latency.observe(0.0)
        conn.reply_nowait(
            msgid, "RequestWorkerLease", self._grant_reply(handle, lease_id)
        )

    def _return_worker_sync(self, conn, msgid, p) -> None:
        """ReturnWorker is synchronous end to end (ledger flip, resource
        refund, idle-pool push): reply inline, skip the dispatch task."""
        self._release_lease(p["lease_id"], p.get("dirty", False))
        conn.reply_nowait(msgid, "ReturnWorker", {"ok": True})

    def _cancel_worker_lease_sync(self, conn, msgid, p) -> None:
        conn.reply_nowait(
            msgid, "CancelWorkerLease", self._cancel_lease_inline(p["lease_id"])
        )

    async def _request_worker_lease(self, conn, p):
        if self._is_duplicate_grant(p["lease_id"]):
            # Duplicate of a lease this raylet already committed to granting
            # (wire-level frame duplication or a client retry): answer
            # idempotently instead of double-granting.
            return await self._duplicate_lease_reply(p["lease_id"])
        demand = ResourceSet.from_units(p.get("resources") or {})
        demand = self._translate_pg_demand(
            demand, p.get("pg_id"), p.get("bundle_index")
        )
        strategy = p.get("strategy") or {}
        # Node affinity (reference: scheduling_options.h NODE_AFFINITY).
        affinity = strategy.get("node_id")
        if affinity and affinity != self.node_id:
            target = None
            for n in await self._cluster_view():
                if n["node_id"] == affinity:
                    if demand.is_subset_of(ResourceSet.from_units(n["total"])):
                        target = {"node_id": affinity, "addr": n["addr"]}
                    break
            if target is not None:
                return self._spill_reply(target)
            if not strategy.get("soft"):
                raise rpc.RpcError(
                    f"node affinity target {affinity[:8]} not in cluster "
                    "or cannot fit the demand"
                )
            affinity = None  # soft fallback: schedule as if unconstrained
        elif affinity == self.node_id and not demand.is_subset_of(self.total):
            if not strategy.get("soft"):
                raise rpc.RpcError(
                    f"demand cannot fit on affinity target {affinity[:8]}"
                )
            affinity = None
        labels = strategy.get("labels")
        if labels:
            # Node-label policy (reference: scheduling_options.h NODE_LABEL
            # + NodeLabelSchedulingStrategy): hard expressions gate
            # eligibility; soft expressions rank among the eligible.
            from ray_tpu.util.scheduling_strategies import node_matches_labels

            if (
                p.get("spilled_from")
                and node_matches_labels(labels.get("hard") or {}, self.labels)
                and demand.is_subset_of(self.total)
            ):
                # Spilled here by a peer's label pick and we qualify: queue
                # locally instead of re-picking (avoids placement ping-pong
                # on lagging views).
                strategy = {k: v for k, v in strategy.items() if k != "labels"}
            else:
                target = await self._label_pick(demand, labels)
                if target is None:
                    raise rpc.RpcError(
                        f"no node matches label constraints {labels['hard']} "
                        "with capacity for the demand"
                    )
                if target["node_id"] != self.node_id:
                    return self._spill_reply(target)
                # Local node is the pick: fall through to queue here.
                strategy = {k: v for k, v in strategy.items() if k != "labels"}
        if not demand.is_subset_of(self.total):
            # Infeasible here — suggest spillback target from GCS view.
            target = await self._find_spillback_node(demand)
            if target is not None:
                return self._spill_reply(target)
            # Cluster-wide infeasible: park on a SIDE queue and wait rather
            # than fail — the demand shows up in pending_demand, the
            # autoscaler can add a node that fits, and the retry loop spills
            # the request there (reference: infeasible tasks warn and wait;
            # resource_demand_scheduler feeds on their shapes). Not on
            # pending_leases: the grant loop is FIFO and an unsatisfiable
            # head would block every feasible lease behind it.
            logger.warning(
                "infeasible resource demand %s on all current nodes; "
                "queueing until the cluster scales",
                demand.to_dict(),
            )
            req = LeaseRequest(p["lease_id"], demand, p)
            self.infeasible_leases.append(req)
            # Parking is the protocol: the demand feeds pending_demand /
            # the autoscaler, the retry loop spills the request once a
            # fitting node joins, and the client bounds the wait with its
            # lease RPC budget (duplicate-grant dedup makes retries safe).
            return await req.fut  # rpc-flow: disable=unbounded-await
        if not affinity and not p.get("spilled_from"):
            placed_by_locality = False
            hints = p.get("locality") or {}
            if hints:
                # Locality-aware placement (reference: locality-aware lease
                # policy): prefer a node already holding the task's args.
                # Counted once per lease — spilled-over requests never
                # re-enter this block.
                await self._cluster_view()
                pick = self._locality_pick(demand, hints)
                if pick is None:
                    self._tel_locality_misses.inc()
                elif pick["node_id"] != self.node_id:
                    self._tel_locality_hits.inc()
                    return self._spill_reply(pick)
                else:
                    self._tel_locality_hits.inc()
                    placed_by_locality = True
            if not placed_by_locality:
                # Scheduling policy (reference: hybrid_scheduling_policy.cc /
                # scheduling_policy.h SPREAD): decide local-vs-remote before
                # queueing. Spilled-over requests stay put to avoid ping-pong.
                target = await self._policy_pick(demand, strategy)
                if target is not None:
                    return self._spill_reply(target)
        req = LeaseRequest(p["lease_id"], demand, p)
        self.pending_leases.append(req)
        self._try_grant_leases()
        # Same parking contract as the infeasible queue above: resolved by
        # _grant (which repairs ledger state and resolves the future on
        # every failure path), bounded by the client's lease RPC budget.
        return await req.fut  # rpc-flow: disable=unbounded-await

    def _spill_reply(self, target: dict) -> dict:
        self._tel_spillbacks.inc()
        return {"spillback": target}

    # -- scheduling policy (reference: raylet/scheduling/policy/) ------------

    async def _infeasible_retry_loop(self) -> None:
        """Re-evaluate parked cluster-wide-infeasible leases: once a node
        that fits registers (autoscaler scale-up, manual join), spill the
        request to it. Local feasibility (this node grew) re-enters the
        normal grant queue."""
        while True:
            await asyncio.sleep(1.0)
            for req in list(self.infeasible_leases):
                if req.fut.done():
                    self.infeasible_leases.remove(req)
                    continue
                if req.demand.is_subset_of(self.total):
                    self.infeasible_leases.remove(req)
                    self.pending_leases.append(req)
                    self._try_grant_leases()
                    continue
                target = await self._find_spillback_node(req.demand)
                if target is None:
                    continue
                self.infeasible_leases.remove(req)
                if not req.fut.done():
                    req.fut.set_result(self._spill_reply(target))

    @staticmethod
    def _addr_key(addr) -> str:
        return f"{addr[0]}:{addr[1]}"

    def _on_view_head(self, msg: dict) -> None:
        """One scheduling-head broadcast from the GCS: {"v", "epoch", "n",
        "head"} where ``head`` is the head-cap least-utilized alive nodes in
        utilization order. State-based, not delta-based — each message
        replaces the previous head wholesale, so there is no sequence to
        gap-detect and a dropped broadcast only costs freshness until the
        next one. O(head cap) per flush regardless of cluster size."""
        v = msg.get("v", -1)
        if v <= self._head_version:
            return  # stale replay / out-of-order
        head = msg.get("head")
        if head is None:
            return
        self._head_version = v
        self._head = head
        self._head_n = msg.get("n", len(head))
        self._head_epoch = msg.get("epoch", -1)
        self._head_addr_map = None  # rebuilt lazily (locality path only)

    def _head_by_addr(self, key: str) -> Optional[dict]:
        m = self._head_addr_map
        if m is None:
            m = self._head_addr_map = {
                self._addr_key(n["addr"]): n for n in self._head
            }
        return m.get(key)

    async def _cluster_view(self) -> list:
        """Full GCS node view for the cold paths (node affinity, label
        pick, locality hints beyond the head, spillback fallback):
        pull-based with a ~1s TTL, refetched immediately when the GCS shape
        epoch moved past our snapshot (membership/total change — a ring or
        affinity decision must not run on departed-node data)."""
        now = time.monotonic()
        epoch_stale = (
            self._head_epoch >= 0
            and self._view_fetched_epoch != self._head_epoch
        )
        if now - self._view_time > 1.0 or epoch_stale:
            if self._view_fetch is None:
                self._view_fetch = rpc.spawn(self._fetch_view())
            # CancelledError propagates (handler cancellation must win);
            # fetch errors leave the stale view in place.
            await asyncio.shield(self._view_fetch)
        return self._view

    async def _fetch_view(self) -> None:
        try:
            reply = await self.gcs.call("GetAllNodes")
            alive = [n for n in reply["nodes"] if n["state"] == "ALIVE"]
            self._view = alive
            self._view_map = {n["node_id"]: n for n in alive}
            self._view_addr = {
                self._addr_key(n["addr"]): n["node_id"] for n in alive
            }
            self._view_time = time.monotonic()
            self._view_fetched_epoch = reply.get("epoch", -1)
        except rpc.RpcError:
            pass
        finally:
            self._view_fetch = None

    async def _node_by_id(self, node_id: str):
        for n in await self._cluster_view():
            if n["node_id"] == node_id:
                return {"node_id": node_id, "addr": n["addr"]}
        return None

    @staticmethod
    def _node_total_rs(node: dict) -> ResourceSet:
        """Lazily parsed ResourceSet for a view node's totals, cached on
        the node dict (which is replaced wholesale on every delta, so the
        cache invalidates for free)."""
        rs = node.get("_total_rs")
        if rs is None:
            rs = node["_total_rs"] = ResourceSet.from_units(node["total"])
        return rs

    @staticmethod
    def _node_avail_rs(node: dict) -> ResourceSet:
        rs = node.get("_avail_rs")
        if rs is None:
            rs = node["_avail_rs"] = ResourceSet.from_units(node["available"])
        return rs

    @staticmethod
    def _node_util(total: Dict[str, int], available: Dict[str, int]) -> float:
        util = 0.0
        for k, tot in total.items():
            if tot > 0 and not k.startswith("node:"):
                util = max(util, 1.0 - available.get(k, 0) / tot)
        return util

    def _local_util(self) -> float:
        # Read the unit dicts directly (no defensive copies): _node_util
        # only iterates, and this runs once per grant on the fast path.
        return self._node_util(self.total._units, self.available._units)

    async def _policy_pick(self, demand: ResourceSet, strategy: dict):
        """Pick a remote target per policy, or None to queue locally.

        Hybrid (default, reference hybrid_scheduling_policy.cc): pack locally
        while local utilization stays at or below the spread threshold; past
        it, move work to a random choice among the top-k least-utilized
        feasible nodes (randomization spreads herds of simultaneous
        schedulers). SPREAD: always place on the least-loaded feasible node,
        round-robin-ish via the same top-k randomization.

        Per-lease work is O(k), not O(nodes): candidates come from the
        GCS-sorted scheduling head the syncer broadcasts, and the SPREAD
        ring is cached per (shape epoch, demand shape).
        """
        import random

        spread = strategy.get("spread", False)
        local_fits = demand.is_subset_of(self.available)
        if spread:
            # SPREAD: rotate over every node whose TOTAL fits the demand
            # (a lagging availability view must not collapse the rotation
            # onto one node). Ring membership only changes with cluster
            # membership/capacity, so the full-view scan is paid per shape
            # epoch, not per lease.
            key = tuple(sorted(demand.to_units().items()))
            cached = self._spread_ring
            epoch = self._head_epoch
            if cached is not None and cached[0] == epoch and cached[1] == key:
                ring = cached[2]
            else:
                ring = [
                    n
                    for n in await self._cluster_view()
                    if demand.is_subset_of(self._node_total_rs(n))
                ]
                ring.sort(key=lambda n: n["node_id"])
                self._spread_ring = (epoch, key, ring)
            if not ring:
                return None
            pick = ring[self._spread_rr % len(ring)]
            self._spread_rr += 1
            if pick["node_id"] == self.node_id:
                return None
            return {"node_id": pick["node_id"], "addr": pick["addr"]}
        if local_fits and self._local_util() <= config.scheduler_spread_threshold:
            return None
        # Walk the GCS-sorted head ascending and stop after k feasible
        # candidates — the k least-utilized nodes that can run the demand
        # right now. Cold start (no broadcast yet): sort the pulled view.
        head = self._head
        n_alive = self._head_n
        if not head:
            head = sorted(
                await self._cluster_view(),
                key=lambda n: self._node_util(n["total"], n["available"]),
            )
            n_alive = len(head)
        k = max(1, int(n_alive * config.scheduler_top_k_fraction))
        cands = []
        for n in head:
            if n["node_id"] == self.node_id:
                continue
            if demand.is_subset_of(self._node_avail_rs(n)):
                cands.append(
                    (
                        n.get("util", self._node_util(n["total"], n["available"])),
                        n,
                    )
                )
                if len(cands) >= k:
                    break
        if not cands:
            return None
        below = [
            c for c in cands if c[0] < config.scheduler_spread_threshold
        ]
        pool = below or cands
        pick_util, pick = random.choice(pool)
        if local_fits and self._local_util() <= pick_util:
            return None  # we're no worse than the best remote; stay local
        return {"node_id": pick["node_id"], "addr": pick["addr"]}

    def _locality_pick(self, demand: ResourceSet, hints: Dict[str, float]):
        """Locality-aware placement: among the nodes named by the task's arg
        locations (addr-keyed weights from the owner), pick the
        heaviest-weighted one that can run the demand RIGHT NOW — requiring
        current availability keeps a saturated arg holder from queueing the
        lease behind its backlog. Returns the pick ({"node_id", "addr"};
        node_id == ours means stay local) or None when no hinted node is
        feasible (a locality miss; the regular policy decides)."""
        local_w = -1.0
        self_key = self._addr_key(self.server.address)
        if self_key in hints and demand.is_subset_of(self.available):
            local_w = hints[self_key]
        best_n = None
        best_w = -1.0
        for key, w in hints.items():
            if key == self_key:
                continue
            # Head entries carry fresher availability than the TTL'd view —
            # overlay them over the pulled snapshot.
            n = self._head_by_addr(key)
            if n is None:
                nid = self._view_addr.get(key)
                n = self._view_map.get(nid) if nid is not None else None
            if n is None or not demand.is_subset_of(self._node_avail_rs(n)):
                continue
            if w > best_w:
                best_n, best_w = n, w
        if local_w >= best_w and local_w >= 0:
            # Ties prefer local: the bytes are already here and the grant
            # skips a spillback hop.
            return {"node_id": self.node_id, "addr": list(self.server.address)}
        if best_n is not None:
            return {"node_id": best_n["node_id"], "addr": best_n["addr"]}
        return None

    async def _label_pick(self, demand: ResourceSet, labels: dict):
        """NODE_LABEL policy: hard-eligible nodes, soft-matching preferred,
        least-utilized wins (capacity-feasible now preferred over
        total-feasible). Returns None when no node can ever satisfy."""
        from ray_tpu.util.scheduling_strategies import node_matches_labels

        hard = labels.get("hard") or {}
        soft = labels.get("soft") or {}
        eligible = []
        for n in await self._cluster_view():
            if not node_matches_labels(hard, n.get("labels") or {}):
                continue
            if not demand.is_subset_of(ResourceSet.from_units(n["total"])):
                continue
            eligible.append(n)
        if not eligible:
            return None
        if soft:
            preferred = [
                n
                for n in eligible
                if node_matches_labels(soft, n.get("labels") or {})
            ]
            pool = preferred or eligible
        else:
            pool = eligible
        now_fits = [
            n
            for n in pool
            if demand.is_subset_of(ResourceSet.from_units(n["available"]))
        ]
        pool = now_fits or pool
        pool.sort(
            key=lambda n: self._node_util(n["total"], n["available"])
        )
        pick = pool[0]
        return {"node_id": pick["node_id"], "addr": pick["addr"]}

    async def _cancel_worker_lease(self, conn, p):
        """Cancel a queued (ungranted) lease request: the surplus-request
        drain that keeps recycled-lease pools from pinning the raylet queue
        (reference: NodeManagerService CancelWorkerLease)."""
        return self._cancel_lease_inline(p["lease_id"])

    def _cancel_lease_inline(self, lease_id: str) -> dict:
        if self.granted_lease_ids.get(lease_id):
            # Already committed to granting: too late to cancel. Any queued
            # duplicate of this id mirrors the grant reply instead — setting
            # it "cancelled" here could beat the grant reply to the shared
            # msgid and strand a granted worker the client abandoned.
            return {"ok": True}
        for req in list(self.pending_leases) + list(self.infeasible_leases):
            # Resolve EVERY queued copy: wire duplication can queue the same
            # lease id twice, and a survivor would be granted to a client
            # that has moved on.
            if req.lease_id == lease_id and not req.fut.done():
                req.fut.set_result({"cancelled": True})
        # Burn the id so a late-arriving duplicate frame cannot re-queue a
        # grantable request for it.
        self._burn_lease_id(lease_id)
        self._tel_lease_cancelled.inc()
        telemetry.record_event(
            "raylet", "lease_cancelled", lease_id=lease_id, node=self.node_id[:8]
        )
        return {"ok": True}

    _GRANT_LEDGER_CAP = 4096

    def _tel_refresh_gauges(self) -> None:
        """Re-sample the worker-pool/lease gauges (three float stores);
        called from every pool or lease-table mutation site."""
        self._tel_workers.set(len(self.workers))
        self._tel_workers_idle.set(len(self.idle_workers))
        self._tel_leases_active.set(len(self.leases))

    def _record_granted(self, lease_id: str) -> None:
        self.granted_lease_ids[lease_id] = True  # True = live (not released)
        self._tel_lease_granted.inc()
        telemetry.record_event(
            "raylet", "lease_granted", lease_id=lease_id, node=self.node_id[:8]
        )
        while len(self.granted_lease_ids) > self._GRANT_LEDGER_CAP:
            self.granted_lease_ids.popitem(last=False)

    def _mark_lease_released(self, lease_id: str) -> None:
        if lease_id in self.granted_lease_ids:
            self.granted_lease_ids[lease_id] = False

    def _burn_lease_id(self, lease_id: str) -> None:
        """Record a lease id as spent without a live grant (cancelled): task
        ids are single-use, so any later request for it is a duplicate and
        resolves ``cancelled`` instead of granting."""
        self.granted_lease_ids[lease_id] = False
        while len(self.granted_lease_ids) > self._GRANT_LEDGER_CAP:
            self.granted_lease_ids.popitem(last=False)

    def _is_duplicate_grant(self, lease_id: str) -> bool:
        """True when granting this id (again) would double-grant. Task lease
        ids are unique per request, so any ledger entry — live or released —
        marks a duplicate. Actor lease ids are legitimately reused on
        restart, so only a LIVE entry counts."""
        if self._mutate_double_grant:
            return False  # seeded bug: forget every previous grant
        state = self.granted_lease_ids.get(lease_id)
        if state is None:
            return False
        return state if lease_id.startswith("actor:") else True

    async def _duplicate_lease_reply(self, lease_id: str) -> dict:
        """Reply for a duplicate request for an already-committed lease id.

        The committed grant may still be in flight (worker spawning), and
        duplicated frames share a msgid — whichever reply lands first wins at
        the client. Answering ``cancelled`` while the real grant resolves
        would make the client abandon a lease the raylet then completes
        (wedged task + leaked worker), so wait for the outcome: reply
        idempotently with the granted worker, or ``cancelled`` once the
        grant failed or the lease was already released.
        """
        self.duplicate_lease_grants_avoided += 1
        self._tel_lease_duplicate.inc()
        telemetry.record_event(
            "raylet", "lease_duplicate", lease_id=lease_id, node=self.node_id[:8]
        )
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 30.0
        while loop.time() < deadline:
            handle = self.leases.get(lease_id)
            if handle is not None and handle.addr is not None:
                # Mirror only a grant whose worker link is still up. A leased
                # worker that died keeps its leases[] entry until the reaper
                # runs, but callers learn of the death sooner (its GCS/raylet
                # conns drop on exit) and re-request the lease; mirroring the
                # doomed grant would hand them a dead worker they wait on
                # forever (seen as a GCS actor parked in RESTARTING).
                if (
                    handle.worker_id in self.workers
                    and handle.conn is not None
                    and not handle.conn.closed
                ):
                    return self._grant_reply(handle, lease_id)
            if not self.granted_lease_ids.get(lease_id, False):
                break  # grant failed, or lease released: nothing to mirror
            await asyncio.sleep(0.01)
        return {"cancelled": True}

    def _resolve_duplicate_lease(self, req: LeaseRequest) -> None:
        # Supervision is internal: the coroutine resolves req.fut on every
        # path, including exceptions from the mirror wait.
        rpc.spawn(self._resolve_duplicate_lease_async(req))  # rpc-flow: disable=unsupervised-spawn

    async def _resolve_duplicate_lease_async(self, req: LeaseRequest) -> None:
        try:
            reply = await self._duplicate_lease_reply(req.lease_id)
        except Exception as e:
            # A crashed mirror must still resolve the future — the client
            # is parked on it and would otherwise wait forever.
            if not req.fut.done():
                req.fut.set_exception(
                    rpc.RpcError(f"duplicate-lease resolution failed: {e!r}")
                )
            return
        if not req.fut.done():
            req.fut.set_result(reply)

    def _try_grant_leases(self) -> None:
        granted_any = True
        while granted_any and self.pending_leases:
            granted_any = False
            req = self.pending_leases[0]
            if req.fut.done():
                self.pending_leases.pop(0)
                granted_any = True
                continue
            if self._is_duplicate_grant(req.lease_id):
                # Already committed to granting this id (duplicated frame or
                # client retry): granting again would double-deduct resources
                # and overwrite leases[id], leaking the first worker.
                self.pending_leases.pop(0)
                self._resolve_duplicate_lease(req)
                granted_any = True
                continue
            if req.demand.is_subset_of(self.available):
                self.pending_leases.pop(0)
                self.available = self.available - req.demand
                self._mark_dirty()
                # Record the commitment BEFORE the async grant runs so a
                # same-id request queued behind us in this very loop pass is
                # already visible as a duplicate.
                self._record_granted(req.lease_id)
                req.grant_started = time.monotonic()
                self.grants_in_flight += 1
                # Supervision is internal: _grant_inner refunds resources,
                # clears the grant ledger, and resolves req.fut on every
                # failure path (except Exception) — this task IS the
                # grant's supervisor.
                rpc.spawn(self._grant(req))  # rpc-flow: disable=unsupervised-spawn
                granted_any = True

    async def _grant(self, req: LeaseRequest) -> None:
        try:
            await self._grant_inner(req)
        finally:
            # Resources are deducted at spawn time but only visible in
            # `leases` once the grant resolves; the counter lets observers
            # (quiescence checks, stats) see the in-between state.
            self.grants_in_flight -= 1

    async def _grant_inner(self, req: LeaseRequest) -> None:
        container = (
            ((req.payload.get("spec") or {}).get("runtime_env") or {})
            .get("container")
        )
        from ray_tpu.util import tracing

        try:
            with tracing.span_scope(
                "lease.worker_start", "lease", ctx=req.trace_ctx,
                lease_id=req.lease_id,
            ):
                if container:
                    # Containerized actors get a dedicated fresh worker
                    # booted inside the image — shared pool workers cannot
                    # switch containers mid-process.
                    handle = await self._start_worker(container=container)
                    await handle.registered
                else:
                    # A worker dying between spawn and registration is a
                    # transient of process storms, not a property of the
                    # lease: retry with a fresh worker before failing the
                    # request.
                    attempt = 0
                    while True:
                        try:
                            handle = await self._get_or_start_idle_worker()
                            break
                        except rpc.RpcError:
                            attempt += 1
                            if attempt >= 3:
                                raise
                            await asyncio.sleep(0.1 * attempt)
        except Exception as e:
            # Not just RpcError: worker spawn can raise OSError (exec
            # failure, fd exhaustion) and an escaping exception here would
            # leak the deducted resources and leave req.fut unresolved —
            # the client parks forever on a lease nobody is granting.
            self.available = self.available + req.demand
            self._mark_dirty()
            # The grant never happened: clear the ledger entry so a genuine
            # client retry with the same id is not refused forever.
            self.granted_lease_ids.pop(req.lease_id, None)
            if not req.fut.done():
                req.fut.set_exception(
                    e
                    if isinstance(e, rpc.RpcError)
                    else rpc.RpcError(f"lease grant failed: {e!r}")
                )
            return
        if req.lease_id in self.leases and not self._mutate_double_grant:
            # Double grant (two _grant tasks raced to the same lease id —
            # the write-write the AIOCHECK probe caught live). The first
            # write owns the lease; this grant is a no-op: re-credit the
            # demand and return the just-acquired worker to the pool.
            self.available = self.available + req.demand
            self._mark_dirty()
            if container:
                # Dedicated containerized worker: not pool-reusable.
                self._kill_worker_proc(handle)
            else:
                self._return_worker_to_pool(handle)
            self._resolve_duplicate_lease(req)
            self._try_grant_leases()
            return
        handle.lease_id = req.lease_id
        handle.demand = req.demand  # type: ignore[attr-defined]
        handle.leased_since = time.monotonic()  # type: ignore[attr-defined]
        handle.job_id = req.payload.get("job_id") or handle.job_id
        self.leases[req.lease_id] = handle
        self._tel_refresh_gauges()
        if not req.fut.done():
            now_m = time.monotonic()
            self._tel_grant_latency.observe(now_m - req.queued_at)
            if req.trace_ctx is not None:
                # Lease-lifecycle spans, parented into the requesting task's
                # trace: one umbrella span for request->grant, with the
                # queue wait and the grant work as its children.
                gs = req.grant_started if req.grant_started is not None else now_m
                sid = tracing.record_span(
                    "raylet.lease",
                    "lease",
                    req.queued_wall,
                    now_m - req.queued_at,
                    ctx=req.trace_ctx,
                    lease_id=req.lease_id,
                )
                child = (req.trace_ctx[0], sid)
                tracing.record_span(
                    "lease.queue",
                    "lease",
                    req.queued_wall,
                    gs - req.queued_at,
                    ctx=child,
                    lease_id=req.lease_id,
                )
                tracing.record_span(
                    "lease.grant",
                    "lease",
                    req.queued_wall + (gs - req.queued_at),
                    now_m - gs,
                    ctx=child,
                    lease_id=req.lease_id,
                    worker_id=handle.worker_id,
                )
            req.fut.set_result(self._grant_reply(handle, req.lease_id))
        else:  # caller gave up; return resources
            self._release_lease(req.lease_id, dirty=False)

    # Pre-packed grant-reply skeleton: the five keys (and the constant
    # granted=true) of every grant reply, packed once at import. Each grant
    # splices only its per-lease values between the skeleton segments —
    # byte-identical to msgpack-packing the dict (insertion order below
    # matches the segment order), as tests/test_fastpath_native.py asserts.
    _GRANT_SKEL = (
        b"\x85" + rpc._packb("granted") + b"\xc3" + rpc._packb("worker_id"),
        rpc._packb("worker_addr"),
        rpc._packb("lease_id"),
        rpc._packb("fp_port"),
    )

    def _grant_reply(self, handle: WorkerHandle, lease_id: str) -> dict:
        worker_addr = list(handle.addr)
        mapping = {
            "granted": True,
            "worker_id": handle.worker_id,
            "worker_addr": worker_addr,
            "lease_id": lease_id,
            "fp_port": handle.fp_port,
        }
        skel = self._GRANT_SKEL
        try:
            raw = b"".join(
                (
                    skel[0], rpc._packb(handle.worker_id),
                    skel[1], rpc._packb(worker_addr),
                    skel[2], rpc._packb(lease_id),
                    skel[3], rpc._packb(handle.fp_port),
                )
            )
        except Exception:  # unpackable oddity: let the frame packer handle it
            return mapping
        return rpc.PackedPayload(mapping, raw)

    def _return_worker_to_pool(self, handle: WorkerHandle) -> None:
        """Return a worker acquired for a grant that will not happen (the
        duplicate-grant no-op path). Mirrors the clean half of
        _release_lease without touching the lease table."""
        handle.lease_id = None
        handle.job_id = None
        if (
            handle.actor_id is None
            and handle.worker_id in self.workers
            and handle.conn is not None
            and not handle.conn.closed
        ):
            handle.idle_since = time.monotonic()
            self.idle_workers.append(handle)
        else:
            self._kill_worker_proc(handle)
        self._tel_refresh_gauges()

    def _free_lease_resources(self, handle: WorkerHandle) -> None:
        demand = getattr(handle, "demand", None)
        if demand is not None:
            self.available = self.available + demand
            handle.demand = None  # type: ignore[attr-defined]
            self._mark_dirty()
            self._try_grant_leases()

    def _release_lease(self, lease_id: str, dirty: bool) -> Optional[WorkerHandle]:
        handle = self.leases.pop(lease_id, None)
        self._mark_lease_released(lease_id)
        if handle is None:
            return None
        self._tel_lease_released.inc()
        telemetry.record_event(
            "raylet",
            "lease_released",
            lease_id=lease_id,
            node=self.node_id[:8],
            dirty=bool(dirty),
        )
        handle.lease_id = None
        if handle.actor_id is None:
            # Pooled worker returning to idle: drop the lease's job
            # attribution so log lines and the memory-kill policy never
            # blame a previous tenant.
            handle.job_id = None
        self._free_lease_resources(handle)
        if dirty or handle.actor_id:
            self._kill_worker_proc(handle)
        elif handle.worker_id in self.workers:
            handle.idle_since = time.monotonic()
            self.idle_workers.append(handle)
        self._tel_refresh_gauges()
        return handle

    async def _return_worker(self, conn, p):
        self._release_lease(p["lease_id"], p.get("dirty", False))
        return {"ok": True}

    async def _find_spillback_node(self, demand: ResourceSet):
        """Least-utilized peer whose TOTAL fits the demand, preferring one
        whose current availability fits. Served from the GCS-sorted
        scheduling head — the old implementation issued a GetAllNodes RPC
        and scanned every node per lease, which melts at hundreds of nodes.
        Only when nothing in the head fits (a demand shape the least-loaded
        nodes can't hold, e.g. a TPU lease amid idle CPU hosts) does it walk
        the full TTL'd view."""
        fallback = None
        for n in self._head:
            if n["node_id"] == self.node_id:
                continue
            if not demand.is_subset_of(self._node_total_rs(n)):
                continue
            if demand.is_subset_of(self._node_avail_rs(n)):
                return {"node_id": n["node_id"], "addr": n["addr"]}
            if fallback is None:
                fallback = {"node_id": n["node_id"], "addr": n["addr"]}
        if fallback is not None:
            return fallback
        best = None
        best_util = 2.0
        for n in await self._cluster_view():
            if n["node_id"] == self.node_id:
                continue
            if not demand.is_subset_of(self._node_total_rs(n)):
                continue
            util = self._node_util(n["total"], n["available"])
            if demand.is_subset_of(self._node_avail_rs(n)):
                util -= 1.0  # available-now beats merely total-feasible
            if util < best_util:
                best, best_util = n, util
        if best is not None:
            return {"node_id": best["node_id"], "addr": best["addr"]}
        return None

    async def _lease_worker_for_actor(self, conn, p):
        """GCS-driven actor placement: lease a worker and hand it the
        creation spec; the worker reports readiness to the GCS itself."""
        spec = p["spec"]
        demand = ResourceSet.from_units(spec.get("resources") or {})
        demand = self._translate_pg_demand(
            demand, spec.get("pg_id"), spec.get("bundle_index")
        )
        if not demand.is_subset_of(self.total):
            return {"granted": False}
        lease_id = "actor:" + spec["actor_id"]
        if lease_id in self.actor_creations_in_flight:
            # A wire-duplicated/retried placement racing the original: the
            # first grant (and its CreateActor) owns the worker — mirror its
            # outcome rather than double-granting.
            return await self._duplicate_lease_reply(lease_id)
        if self._is_duplicate_grant(lease_id):
            # No creation in flight, yet the id has a live lease: this is a
            # GCS-driven RE-placement (restart FSM, or post-failover
            # reconciliation that declared our node dead), not a duplicate
            # frame. The new placement is authoritative — reclaim the stale
            # instance and grant fresh. Detach actor_id first so reaping the
            # old proc isn't reported as an actor death (it moved, it didn't
            # die — a report would trigger a spurious second restart).
            stale = self.leases.get(lease_id)
            if stale is not None:
                stale.actor_id = None
                self._release_lease(lease_id, dirty=True)
            else:
                self._burn_lease_id(lease_id)
        self.actor_creations_in_flight.add(lease_id)
        try:
            req = LeaseRequest(lease_id, demand, p)
            self.pending_leases.append(req)
            self._try_grant_leases()
            reply = await req.fut
            if not reply.get("granted"):
                return reply
            handle = self.leases[req.lease_id]
            handle.actor_id = spec["actor_id"]
            handle.job_id = spec.get("job_id")
            try:
                await handle.conn.call(
                    "CreateActor",
                    {"spec": spec},
                    timeout=config.rpc_actor_create_timeout_s,
                )
            except rpc.RpcError as e:
                self._release_lease(req.lease_id, dirty=True)
                return {"granted": False, "error": str(e)}
            return {"granted": True, "worker_id": handle.worker_id}
        finally:
            self.actor_creations_in_flight.discard(lease_id)

    async def _kill_worker(self, conn, p):
        handle = self.workers.get(p["worker_id"])
        if p.get("probe"):
            # Liveness probe only (GCS post-restart actor reconciliation).
            alive = handle is not None and (
                handle.proc is None  # fork in flight but registered
                or handle.proc.returncode is None
            )
            return {"ok": True, "alive": alive}
        if handle is None:
            return {"ok": False}
        if p.get("force") and handle.proc is not None:
            # ray.kill(): SIGKILL, no atexit handlers (wire.py: KillWorker).
            # The wire checker surfaced that producers set force=True but the
            # handler always soft-terminated.
            try:
                handle.proc.kill()
            except ProcessLookupError:
                pass
        else:
            self._kill_worker_proc(handle)
        return {"ok": True}

    # -- object store --------------------------------------------------------
    # One shm arena per node; the StoreCore (C++ when built) owns offsets,
    # seal/pin state and LRU order — reference: plasma store
    # (object_lifecycle_manager.cc / plasma_allocator.cc / eviction_policy.cc).

    def _obj_meta(self, oid: str, info) -> dict:
        return {
            "arena": self.arena_name,
            "offset": info[0],
            "size": info[1],
        }

    async def _condemned_sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            self._sweep_condemned()
            # Expire inbound pushes with no chunk progress (source wedged
            # without disconnecting): 60s of silence vastly exceeds any
            # chunk cadence the push budget allows.
            now = time.monotonic()
            for oid, st in list(self.push_assembly.items()):
                if now - st.get("last", now) > 60.0:
                    logger.warning(
                        "aborting stalled inbound push of %s (%d/%d bytes)",
                        oid[:12], st["recv"], st["size"],
                    )
                    self._abort_push_assembly(oid)

    def _sweep_condemned(self, force: bool = False) -> None:
        """Return quarantined spans to the allocator once the grace window has
        passed (no client should still be holding a view)."""
        now = time.monotonic()
        grace = config.object_store_eviction_grace_s
        for oid, t in list(self.condemned.items()):
            if (
                oid in self.obj_holds
                or oid in self.restoring
                or oid in self.push_assembly
            ):
                # A client still maps it, a restore IO thread is writing the
                # span, or an inbound push is mid-assembly — reclaim once
                # that settles (assemblies abort on the next chunk/expiry).
                continue
            if force or now - t >= grace:
                self.store.free(oid)
                del self.condemned[oid]

    def _delete_object(self, oid: str) -> None:
        """Logical delete: the object disappears from the directory now. With
        no client holds the span frees immediately (holds are the only source
        of zero-copy views, so nothing can still map the bytes); held objects
        are quarantined until the grace window passes. Immediate reuse keeps
        sustained large-put workloads on already-faulted arena pages."""
        self._drop_spilled(oid)
        self.pinned_objects.discard(oid)
        info = self.store.lookup(oid)
        if oid in self.condemned or info is None:
            return
        self.obj_last_access.pop(oid, None)
        for fut in self.obj_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(False)
        # Sealed + hold-free: nothing can still map the bytes (holds are the
        # only source of zero-copy reader views, and the writer's view is
        # gone once sealed). Unsealed objects may have a writer mid-memcpy
        # (e.g. a task return whose ref was dropped early) — quarantine those
        # for the grace window instead.
        if info[2] and oid not in self.obj_holds:
            self.store.free(oid)
        else:
            self.condemned[oid] = time.monotonic()

    def _try_alloc(self, oid: str, size: int, pin: bool) -> int:
        """Alloc with eviction retries. Victims: condemned objects past grace
        first, then LRU sealed+unpinned objects past grace, then SPILL of
        sealed objects (pinned primary copies included) to disk. Retrying
        alloc after every free makes the loop robust to rounding/fragmentation
        (byte accounting alone cannot prove a span fits)."""
        offset = self.store.alloc(oid, size, pin)
        if offset >= 0:
            return offset
        self._sweep_condemned()
        offset = self.store.alloc(oid, size, pin)
        if offset >= 0:
            return offset
        now = time.monotonic()
        grace = config.object_store_eviction_grace_s
        candidates = []
        for vic, last in self.obj_last_access.items():
            if (
                now - last < grace
                or vic in self.obj_holds
                or vic in self.spilling
                or vic in self.pinned_objects
            ):
                continue
            info = self.store.lookup(vic)
            if info is not None and info[2] and not info[3]:
                candidates.append((last, vic))
        candidates.sort()
        for _, vic in candidates:
            self.store.free(vic)
            _TEL_OBJ_EVICTED.inc()
            telemetry.record_event(
                "object", "freed", oid=vic[:16], node=self.node_id[:8],
                reason="lru_evict",
            )
            self.obj_last_access.pop(vic, None)
            offset = self.store.alloc(oid, size, pin)
            if offset >= 0:
                return offset
        # Still no room: start spilling sealed, unheld objects (LRU-first).
        # Spill IO is asynchronous (thread pool) — the span only frees once
        # the write lands, so report failure now and let the caller's retry
        # loop (ObjCreate backpressure / restore retries) pick up the freed
        # space. Reference: LocalObjectManager::SpillObjectsOfSize + async IO
        # workers (local_object_manager.cc).
        self._start_spills(size)
        return -1

    # -- spilling (reference: local_object_manager.cc, external_storage.py) --

    def _start_spills(self, need_bytes: int) -> None:
        """Schedule spill writes until in-flight spills cover ``need_bytes``
        (or no candidates remain). Largest-first: freeing the demanded bytes
        with the fewest IO round-trips minimizes per-object spill overhead
        and leaves the most small hot objects resident (reference:
        LocalObjectManager::SpillObjectsOfSize picks until the byte target).
        Ref-aware: never a client-held, condemned, pinned, or in-flight
        spilling/restoring object."""
        in_flight = 0
        for vic in self.spilling:
            info = self.store.lookup(vic)
            if info is not None:
                in_flight += info[1]
        if in_flight >= need_bytes:
            return
        candidates = []
        for vic, last in self.obj_last_access.items():
            if (
                vic in self.obj_holds
                or vic in self.condemned
                or vic in self.spilling
                or vic in self.restoring
                or vic in self.pinned_objects
            ):
                continue
            info = self.store.lookup(vic)
            if info is not None and info[2]:
                candidates.append((info[1], last, vic))
        # Largest first; LRU (oldest access) breaks size ties.
        candidates.sort(key=lambda c: (-c[0], c[1]))
        for vsize, _, vic in candidates:
            self.spilling[vic] = rpc.spawn(self._spill_task(vic))
            in_flight += vsize
            if in_flight >= need_bytes:
                break

    async def _spill_task(self, oid: str) -> None:
        """One spill write: copy arena bytes out via the storage backend on
        the IO pool, then free the span — unless the object was deleted or
        grabbed by a client while the write was in flight."""
        try:
            info = self.store.lookup(oid)
            if info is None or not info[2]:
                return
            off, size, _, pinned = info
            view = self.arena.view[off : off + size]
            loop = asyncio.get_running_loop()
            t0 = time.monotonic()
            try:
                uri = await loop.run_in_executor(
                    self._io_pool, self.storage.spill, oid, view
                )
            except Exception:
                logger.exception("spill of %s failed", oid[:12])
                return
            self._tel_spill_latency.observe(time.monotonic() - t0)
            # Re-check: a delete/condemn, a new client hold, or a
            # delete-then-recreate (same oid, new span — detectable as a
            # changed offset/size or an unsealed state) during the write
            # means the external copy is stale or the arena copy is still
            # the live one — discard the external copy.
            info2 = self.store.lookup(oid)
            if (
                info2 is None
                or info2[0] != off
                or info2[1] != size
                or not info2[2]
                or oid in self.condemned
                or oid in self.obj_holds
                or oid in self.spilled
            ):
                await loop.run_in_executor(self._io_pool, self.storage.delete, uri)
                return
            self.spilled[oid] = (uri, size, pinned)
            # Counter rides the keyed self.spilled entry: the re-check above
            # discards the duplicate copy when oid is already spilled, so a
            # retried SpillObjects cannot double-count.
            self.spilled_bytes += size  # exc-flow: disable=retry-unsafe-mutation
            self.store.free(oid)
            self.obj_last_access.pop(oid, None)
            self._tel_spilled_bytes.inc(size)
            telemetry.record_event(
                "object", "spilled", oid=oid[:16], size=size,
                node=self.node_id[:8],
            )
            tracing.record_span(
                "object.spill", "object", time.time() - (time.monotonic() - t0),
                time.monotonic() - t0, oid=oid[:16], size=size,
            )
            logger.info(
                "spilled %s (%d bytes) to %s; store %d/%d",
                oid[:12],
                size,
                uri.split("://", 1)[0],
                self.store.used,
                self.store_capacity,
            )
        finally:
            self.spilling.pop(oid, None)

    async def _restore_object(self, oid: str) -> Optional[int]:
        """Bring a spilled object back into the arena; returns offset or
        None (arena transiently full — caller retries). Concurrent restores
        of one object coalesce on a shared future; the read runs on the IO
        pool so the event loop never blocks on storage."""
        fut = self.restoring.get(oid)
        if fut is not None:
            return await asyncio.shield(fut)
        entry = self.spilled.get(oid)
        if entry is None:
            return None
        uri, size, pinned = entry
        offset = self._try_alloc(oid, size, pinned)
        if offset < 0:
            return None
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.restoring[oid] = fut
        ok = False
        try:
            dest = self.arena.view[offset : offset + size]
            t0 = time.monotonic()
            try:
                n = await loop.run_in_executor(
                    self._io_pool, self.storage.restore, uri, dest
                )
                ok = n == size
            except external_storage.SpillIntegrityError as e:
                # Torn spill file: the external copy is garbage, so this is
                # NOT transient — drop the entry (and the bad bytes) so the
                # object reads as lost and the owner's lineage
                # reconstruction takes over instead of a retry loop sealing
                # corrupt data.
                logger.error("restore of %s hit torn spill file: %s", oid[:12], e)
                telemetry.record_event(
                    "object", "spill_corrupt", oid=oid[:16],
                    node=self.node_id[:8], expected=e.expected, actual=e.actual,
                )
                self._drop_spilled(oid)
                self.store.free(oid)
                fut.set_result(None)
                for w in self.obj_waiters.pop(oid, []):
                    if not w.done():
                        w.set_result(False)
                return None
            except Exception:
                logger.exception("restore of %s failed", oid[:12])
            if oid in self.condemned:
                # Deleted while the read was in flight: abandon the restore;
                # the condemned sweep reclaims the span now that we are no
                # longer writing it.
                self.store.free(oid)
                self.condemned.pop(oid, None)
                fut.set_result(None)
                return None
            if not ok or self.store.lookup(oid) is None:
                # IO errors are treated as transient (a remote backend can
                # 503): keep the spilled entry and the external copy so the
                # caller's backpressure loop can retry; only the caller's
                # deadline turns persistent failure into object-lost.
                self.store.free(oid)
                fut.set_result(None)
                return None
            self.store.seal(oid)
            self.obj_last_access[oid] = time.monotonic()
            self._tel_restore_latency.observe(time.monotonic() - t0)
            self._tel_restored_bytes.inc(size)
            telemetry.record_event(
                "object", "restored", oid=oid[:16], size=size,
                node=self.node_id[:8],
            )
            tracing.record_span(
                "object.restore", "object",
                time.time() - (time.monotonic() - t0),
                time.monotonic() - t0, oid=oid[:16], size=size,
            )
            if self.spilled.pop(oid, None) is not None:
                # Guarded by the keyed pop: the second application sees no
                # entry and skips the decrement.
                self.spilled_bytes -= size  # exc-flow: disable=retry-unsafe-mutation
            # Fire-and-forget: the external copy's deletion must not hold the
            # RPC reply (or fail it after a successful restore).
            try:
                self._io_pool.submit(self.storage.delete, uri)
            except RuntimeError:  # pool already shut down at teardown
                pass
            fut.set_result(offset)
            for w in self.obj_waiters.pop(oid, []):
                if not w.done():
                    w.set_result(True)
            return offset
        finally:
            if not fut.done():
                fut.set_result(None)
            self.restoring.pop(oid, None)

    async def _restore_with_backpressure(self, oid: str) -> None:
        """Restore a spilled object, retrying while the arena is transiently
        full (async spills free room within ~the IO latency). A restore
        failure here must stay transient, not become a spurious copy-lost:
        the bytes still exist in external storage."""
        deadline = time.monotonic() + config.object_store_create_timeout_s
        while oid in self.spilled and oid not in self.condemned:
            if await self._restore_object(oid) is not None:
                return
            if time.monotonic() >= deadline:
                return
            await asyncio.sleep(0.05)

    def _drop_spilled(self, oid: str) -> None:
        entry = self.spilled.pop(oid, None)
        if entry is None:
            return
        # Guarded by the keyed pop above: idempotent under re-delivery.
        self.spilled_bytes -= entry[1]  # exc-flow: disable=retry-unsafe-mutation
        uri = entry[0]
        try:
            self._io_pool.submit(self.storage.delete, uri)
        except RuntimeError:  # pool already shut down at teardown
            pass

    async def _pressure_loop(self) -> None:
        """Proactive spill-under-pressure (reference: LocalObjectManager
        triggered at object_spilling_threshold, local_object_manager.cc):
        instead of waiting for an allocation to fail — which serializes the
        spill IO latency into some put's backpressure loop — spill eligible
        objects (largest-first, via _start_spills) as soon as occupancy
        crosses the threshold, so steady-state oversubscribed workloads
        always find headroom."""
        threshold = config.object_spilling_threshold
        while True:
            await asyncio.sleep(config.object_spilling_poll_interval_s)
            cap = self.store_capacity
            used = self.store.used
            frac = used / cap if cap else 0.0
            self._tel_arena_pressure.set(frac)
            if frac <= threshold:
                continue
            # Spill down to the threshold watermark, counting writes
            # already in flight (they free their spans when the IO lands).
            self._start_spills(used - int(threshold * cap))

    async def _spill_objects(self, conn, p):
        """SpillObjects: owner/tooling directive to move named objects to
        external storage now. Idempotent: an already-spilled oid reports as
        spilled; an ineligible one (unsealed, held, pinned, condemned,
        mid-restore, or unknown) reports as rejected, never an error."""
        scheduled = []
        rejected = []
        for oid in p["oids"]:
            if oid in self.spilled:
                scheduled.append(oid)
                continue
            if oid in self.spilling:
                scheduled.append(oid)
                continue
            info = self.store.lookup(oid)
            if (
                info is None
                or not info[2]
                or oid in self.obj_holds
                or oid in self.condemned
                or oid in self.restoring
                or oid in self.pinned_objects
            ):
                rejected.append(oid)
                continue
            self.spilling[oid] = rpc.spawn(self._spill_task(oid))
            scheduled.append(oid)
        waits = [self.spilling[oid] for oid in scheduled if oid in self.spilling]
        if waits:
            await asyncio.gather(*waits, return_exceptions=True)
        return {
            "spilled": [oid for oid in scheduled if oid in self.spilled],
            "rejected": rejected,
        }

    async def _restore_spilled(self, conn, p):
        """RestoreSpilled: bring one spilled object back into the arena —
        the pull manager's owner-directed fallback before it declares an
        object lost. Coalesces with in-flight restores; a no-op (already
        resident) reports restored=True."""
        oid = p["oid"]
        await self._restore_with_backpressure(oid)
        info = self.store.lookup(oid)
        resident = (
            info is not None and info[2] and oid not in self.condemned
        )
        return {"restored": resident, "spilled": oid in self.spilled}

    async def _pin_object(self, conn, p):
        """PinObject: mark/unmark an object as a pinned primary copy. The
        spill scheduler and LRU eviction skip pinned oids entirely."""
        oid = p["oid"]
        if bool(p.get("pin", True)):
            if not self.store.contains(oid) and oid not in self.spilled:
                return {"ok": False}
            self.pinned_objects.add(oid)
        else:
            self.pinned_objects.discard(oid)
        return {"ok": True}

    # -- memory monitor (reference: memory_monitor.h + worker_killing_policy)

    def _system_memory_fraction(self) -> float:
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    parts = line.split()
                    info[parts[0].rstrip(":")] = int(parts[1])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", 0)
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    async def _memory_monitor_loop(self) -> None:
        while True:
            await asyncio.sleep(config.memory_monitor_interval_s)
            frac = self._system_memory_fraction()
            if frac < config.memory_usage_threshold:
                continue
            victim = self._pick_memory_victim()
            if victim is None:
                continue
            logger.warning(
                "memory usage %.1f%% over threshold %.1f%%: killing worker "
                "%s (%s)",
                frac * 100,
                config.memory_usage_threshold * 100,
                victim.worker_id[:8],
                "newest task worker of largest owner group; owner retries "
                "per max_retries"
                if victim.actor_id is None
                else f"actor {victim.actor_id[:8]}; owner sees restart or "
                "ActorDiedError",
            )
            self._kill_worker_proc(victim)

    def _pick_memory_victim(self) -> Optional["WorkerHandle"]:
        """Group-by-owner fair killing (reference:
        worker_killing_policy_group_by_owner.h / worker_killing_policy.h:34).

        Task workers first (their owners retry per max_retries): group
        leased workers by owning job and pick the NEWEST worker from the
        LARGEST group — the job consuming the most workers sheds load first,
        so one memory-hungry job cannot starve every tenant on the node.
        Actor workers are eligible as a last resort, newest first (their
        owners see a restart or ActorDiedError) — a runaway actor must not
        OOM the node while the monitor watches."""
        newest = lambda h: getattr(h, "leased_since", h.idle_since)  # noqa: E731
        task_workers = [h for h in self.leases.values() if h.actor_id is None]
        if task_workers:
            groups: Dict[Optional[str], List[WorkerHandle]] = {}
            for h in task_workers:
                groups.setdefault(h.job_id, []).append(h)
            largest = max(
                groups.values(), key=lambda g: (len(g), max(newest(h) for h in g))
            )
            return max(largest, key=newest)
        actors = [h for h in self.workers.values() if h.actor_id is not None]
        if actors:
            return max(actors, key=newest)
        return None

    async def _obj_create(self, conn, p):
        """Create (or resolve an existing/spilled copy of) an object span.

        Runs as a retry loop with backpressure (plasma
        create_request_queue.cc analog): when the arena is transiently full
        of client-held objects, room appears as holds release, the eviction
        grace expires, or spill victims free up — so re-evaluate the full
        exists/spilled/alloc state each round rather than failing, since a
        concurrent deterministic recreate may land the object meanwhile."""
        oid, size = p["oid"], p["size"]
        pin = bool(p.get("pin", True))
        deadline = time.monotonic() + config.object_store_create_timeout_s
        while True:
            fut = self.restoring.get(oid)
            if fut is not None:
                # A restore IO thread is writing this span: let it finish
                # before any free/recreate decision (the restored bytes are
                # the deterministically identical object anyway).
                await asyncio.shield(fut)
                continue
            if oid in self.condemned:
                if oid in self.obj_holds:
                    # A client still maps the old (deterministically
                    # identical) bytes: resurrect the quarantined object
                    # instead of freeing a span someone is reading.
                    del self.condemned[oid]
                    self.obj_last_access[oid] = time.monotonic()
                else:
                    # Recreate of a just-deleted id: reclaim that span now.
                    self.store.free(oid)
                    del self.condemned[oid]
            if oid in self.spilled:
                # Deterministic recreate of a spilled object: restore it (may
                # fail transiently while the arena is full of held objects).
                await self._restore_object(oid)
            info = self.store.lookup(oid)
            if info is not None:
                self.obj_last_access[oid] = time.monotonic()
                meta = self._obj_meta(oid, info)
                meta.update({"exists": True, "sealed": info[2]})
                return meta
            if oid not in self.spilled:
                offset = self._try_alloc(oid, size, pin)
                if offset >= 0:
                    self.obj_last_access[oid] = time.monotonic()
                    telemetry.record_event(
                        "object",
                        "created",
                        oid=oid[:16],
                        size=size,
                        node=self.node_id[:8],
                    )
                    return {
                        "arena": self.arena_name,
                        "offset": offset,
                        "size": size,
                        "exists": False,
                    }
            if size > self.store_capacity or time.monotonic() >= deadline:
                raise rpc.RpcError(
                    f"object store full: need {size}, used {self.store.used} "
                    f"of {self.store_capacity} (fragmentation "
                    f"{self.store.fragmentation()[0]:.2f}; spilled "
                    f"{len(self.spilled)} objects / {self.spilled_bytes} "
                    "bytes; objects currently held by clients cannot be "
                    "spilled — raise object_store_memory or release holds)"
                )
            await asyncio.sleep(0.1)

    async def _obj_seal(self, conn, p):
        oid = p["oid"]
        if self.store.lookup(oid) is None:
            raise rpc.RpcError(f"seal of unknown object {oid[:12]}")
        self.store.seal(oid)
        _TEL_OBJ_SEALED.inc()
        telemetry.record_event(
            "object", "sealed", oid=oid[:16], node=self.node_id[:8]
        )
        self.obj_last_access[oid] = time.monotonic()
        for fut in self.obj_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return {"ok": True}

    async def _obj_get(self, conn, p):
        """Resolve local objects; optionally block until sealed."""
        timeout = p.get("timeout")
        found, missing = {}, []
        deadline = time.monotonic() + timeout if timeout else None
        for oid in p["oids"]:
            if oid in self.spilled and oid not in self.condemned:
                # Restore backpressure: the arena may be transiently full of
                # client-held objects; holds release within ~1s (client flush
                # loops), so retry until the caller's deadline — but never
                # past the create-timeout cap: a timeout-less blocking get on
                # a persistently failing restore must surface as missing, not
                # hang the RPC forever.
                restore_cap = (
                    time.monotonic() + config.object_store_create_timeout_s
                )
                while (
                    await self._restore_object(oid) is None
                    and oid in self.spilled
                    and p.get("block", True)
                    and (deadline is None or time.monotonic() < deadline)
                    and time.monotonic() < restore_cap
                ):
                    await asyncio.sleep(0.05)
            info = None if oid in self.condemned else self.store.lookup(oid)
            if info is not None and not info[2] and p.get("block", True):
                fut = asyncio.get_running_loop().create_future()
                self.obj_waiters.setdefault(oid, []).append(fut)
                remaining = (
                    None if deadline is None else max(0, deadline - time.monotonic())
                )
                try:
                    await asyncio.wait_for(fut, remaining)
                except asyncio.TimeoutError:
                    pass
                info = None if oid in self.condemned else self.store.lookup(oid)
            if info is not None and info[2]:
                self.store.touch(oid)
                self.obj_last_access[oid] = time.monotonic()
                self._add_hold(conn, oid)
                found[oid] = self._obj_meta(oid, info)
            else:
                missing.append(oid)
        return {"found": found, "missing": missing}

    async def _obj_contains(self, conn, p):
        return {
            "contains": {
                oid: oid not in self.condemned
                and (self.store.contains(oid) or oid in self.spilled)
                for oid in p["oids"]
            }
        }

    async def _obj_release(self, conn, p):
        for oid in p.get("oids") or [p["oid"]]:
            holds = self.obj_holds.get(oid)
            if holds is not None:
                n = holds.get(id(conn), 0) - 1
                if n <= 0:
                    holds.pop(id(conn), None)
                else:
                    holds[id(conn)] = n
                if not holds:
                    del self.obj_holds[oid]
            if self.store.lookup(oid) is not None:
                self.store.touch(oid)
                self.obj_last_access[oid] = time.monotonic()
        return {"ok": True}

    async def _obj_delete(self, conn, p):
        for oid in p["oids"]:
            self._delete_object(oid)
        return {"ok": True}

    # -- cross-node transfer (reference: object_manager pull/push) -----------

    def _add_hold(self, conn, oid: str) -> None:
        holds = self.obj_holds.setdefault(oid, {})
        holds[id(conn)] = holds.get(id(conn), 0) + 1

    # -- inbound push handlers (reference: object_manager HandlePush) --------

    async def _push_object(self, conn, p):
        """Source side: stream our local copy of an object to a destination
        raylet. Triggered by the destination's pull; the push manager dedups
        concurrent requests and bounds chunks in flight across ALL
        destinations (broadcast-safe fan-out)."""
        await self.push_manager.push(p["oid"], tuple(p["to"]))
        return {"ok": True}

    async def _push_start(self, conn, p):
        """Destination side: allocate an unsealed span for an inbound push.
        Returns needed=False when the object already exists or another
        transfer is assembling it."""
        oid, size = p["oid"], p["size"]
        meta = await self._obj_create(conn, {"oid": oid, "size": size, "pin": False})
        if meta.get("exists") or oid in self.push_assembly:
            return {"needed": False}
        self.push_assembly[oid] = {
            "offset": meta["offset"],
            "size": size,
            "recv": 0,
            "conn": id(conn),
            "last": time.monotonic(),
        }
        return {"needed": True}

    def _push_chunk_sink(self, conn, p, size):
        """Destination side: blob sink factory for one inbound chunk. The
        chunk's bytes stream from the socket straight into the arena span at
        the assembly's write offset (one copy, NIC->arena) instead of
        materializing in a msgpack payload first. Returning None drains and
        discards the blob."""
        oid, off = p["oid"], p["offset"]
        st = self.push_assembly.get(oid)
        if st is None:
            return None  # assembly aborted (e.g. object deleted mid-push)
        if st.get("conn") != id(conn):
            # Chunk from a stale source (an aborted push's connection that
            # un-wedged after a fresh PushStart re-created the assembly):
            # counting it would seal before the live transfer's tail lands.
            return None
        if oid in self.condemned:
            # Deleted mid-assembly: stop writing before the condemned sweep
            # can free the span out from under us.
            del self.push_assembly[oid]
            return None
        if off != st["recv"] or off + size > st["size"]:
            # Out-of-order, duplicated, or over-long chunk. Writing it would
            # either punch a hole (sealing on byte count would then expose
            # uninitialized shm) or run past the span into a neighboring
            # object. The source sends strictly in order, so any deviation
            # means a corrupt/stale stream: abort the whole assembly and let
            # the next pull re-transfer from scratch.
            logger.warning(
                "aborting push assembly of %s: chunk offset %d (expected %d, size %d)",
                oid[:12], off, st["recv"], st["size"],
            )
            self._abort_push_assembly(oid)
            return None
        return _ArenaChunkSink(self, oid, st, off, size)

    def _abort_push_assembly(self, oid: str) -> None:
        """Drop a dead inbound push so the oid does not stay permanently
        unfetchable (exists-unsealed would make every future PushStart answer
        needed=False). Deleting the unsealed object quarantines the span;
        the next pull re-creates and re-transfers it."""
        if self.push_assembly.pop(oid, None) is not None:
            self._delete_object(oid)

    async def _pull_object(self, conn, p):
        """Fetch an object from a remote raylet into the local store.

        Fast path: ask the source to *push* (one-way chunk stream through its
        push manager — broadcast-friendly). Fallback: the legacy chunk pull
        (request/reply FetchChunk loop)."""
        oid = p["oid"]
        await self._restore_with_backpressure(oid)
        info = self.store.lookup(oid)
        if info is not None and info[2]:
            self._add_hold(conn, oid)
            return self._obj_meta(oid, info)
        remote = await rpc.connect(*p["from_addr"], retry=3)
        # Admission (reference: pull_manager.h): learn the size, then wait
        # for quota at this request's priority before moving any bytes.
        probe = await remote.call(
            "ObjGet", {"oids": [oid], "block": True, "timeout": 30}
        )
        probe_meta = probe["found"].get(oid)
        if probe_meta is None:
            # A spilled copy is a valid pull source: before declaring the
            # object absent, direct the holder to restore from its external
            # storage (the probe's internal restore can give up early when
            # its arena is persistently full — an explicit RestoreSpilled
            # retries with fresh backpressure budget).
            try:
                rest = await remote.call(
                    "RestoreSpilled", {"oid": oid},
                    timeout=config.rpc_transfer_timeout_s,
                )
            except (rpc.RpcError, asyncio.TimeoutError, OSError):
                rest = None
            if rest and rest.get("restored"):
                self.pull_manager.restore_fallbacks += 1
                pull_manager_mod._TEL_RESTORE_FALLBACKS.inc()
                probe = await remote.call(
                    "ObjGet", {"oids": [oid], "block": True, "timeout": 30}
                )
                probe_meta = probe["found"].get(oid)
        if probe_meta is None:
            await remote.close()
            raise rpc.RpcError(f"object {oid[:12]} not on remote node")
        pull_size = int(probe_meta.get("size", 0))
        await self.pull_manager.acquire(pull_size, p.get("purpose", "get"))
        try:
            def _recv_progress():
                st = self.push_assembly.get(oid)
                # Track the assembly's byte counter; before PushStart lands
                # (or after a seal removed the entry) report a sentinel so
                # only a *stuck mid-assembly* counter reads as no-progress.
                return -1 if st is None else st["recv"]

            def _sealed():
                info = self.store.lookup(oid)
                return info is not None and info[2] and oid not in self.condemned

            rerequests = 0
            while True:
                try:
                    await remote.call(
                        "PushObject",
                        {"oid": oid, "to": list(self.addr)},
                        timeout=config.rpc_transfer_timeout_s,
                    )
                    # Supervise the one-way chunk stream: a stream that stops
                    # mid-assembly (source death, chunk loss) is aborted and
                    # re-requested instead of riding out the blocking-get
                    # timeout + the 60s assembly janitor.
                    await self.pull_manager.watch_stream(
                        _recv_progress, _sealed, timeout=30
                    )
                    got = await self._obj_get(
                        conn, {"oids": [oid], "block": True, "timeout": 5}
                    )
                    found = got["found"].get(oid)
                    if found is not None:
                        return found  # _obj_get already holds it for this conn
                    break  # sealed then deleted underneath us: fall back
                except PullStalled as e:
                    self._abort_push_assembly(oid)
                    if rerequests >= self.pull_manager.max_rerequests:
                        logger.warning(
                            "push stream for %s stalled %d times (%s); "
                            "falling back to chunk pull",
                            oid[:12], rerequests + 1, e,
                        )
                        break
                    rerequests += 1
                    self.pull_manager.rerequested_streams += 1
                    pull_manager_mod._TEL_REREQUESTED.inc()
                    telemetry.record_event(
                        "object", "pull_rerequest", oid=oid[:16],
                        node=self.node_id[:8], attempt=rerequests,
                    )
                    logger.info(
                        "push stream for %s stalled (%s); re-requesting "
                        "(%d/%d)",
                        oid[:12], e, rerequests, self.pull_manager.max_rerequests,
                    )
                except (rpc.RpcError, asyncio.TimeoutError, OSError) as e:
                    logger.debug(
                        "push-based pull of %s failed (%s); falling back", oid[:12], e
                    )
                    break
            # block briefly: the owner's seal may still be in flight on its
            # raylet connection (puts seal via one-way push).
            reply = await remote.call(
                "ObjGet", {"oids": [oid], "block": True, "timeout": 5}
            )
            meta = reply["found"].get(oid)
            if meta is None:
                raise rpc.RpcError(f"object {oid[:12]} not on remote node")
            size = meta["size"]
            create = await self._obj_create(conn, {"oid": oid, "size": size, "pin": False})
            if create.get("sealed"):
                # Hold for the caller like the sibling paths: an unheld span
                # could be spilled/evicted before the puller reads it.
                self._add_hold(conn, oid)
                return create
            if create.get("exists"):
                # Another pull is filling it; wait for the seal and verify.
                await self._obj_get(conn, {"oids": [oid], "block": True, "timeout": 60})
                info = self.store.lookup(oid)
                if info is None or not info[2] or oid in self.condemned:
                    raise rpc.RpcError(
                        f"concurrent pull of {oid[:12]} did not complete"
                    )
                self._add_hold(conn, oid)
                return create
            offset = create["offset"]
            view = self.arena.view
            chunk = adaptive_chunk_size(size)
            done = 0
            while done < size:
                n = min(chunk, size - done)
                # Blob reply streamed straight into our arena span at the
                # object's offset: the socket bytes land in shm with no
                # intermediate msgpack buffer.
                sink = rpc.SpanSink(view, offset + done)
                await remote.call_into(
                    "FetchChunk",
                    {"oid": oid, "offset": done, "size": n},
                    sink,
                    timeout=config.rpc_chunk_timeout_s,
                )
                if sink.written != n:
                    raise rpc.RpcError(
                        f"short FetchChunk for {oid[:12]}: "
                        f"{sink.written}/{n} bytes at offset {done}"
                    )
                done += n
            await self._obj_seal(conn, {"oid": oid})
            self._add_hold(conn, oid)
            return create
        finally:
            self.pull_manager.release(pull_size)
            await remote.close()

    async def _fetch_chunk(self, conn, p):
        await self._restore_with_backpressure(p["oid"])
        info = self.store.lookup(p["oid"])
        if info is None or not info[2]:
            raise rpc.RpcError(f"object {p['oid'][:12]} not local")
        base = info[0] + p["offset"]
        n = p["size"]
        # Blob reply: the arena view is written to the transport before
        # _dispatch returns to the loop, so no hold is needed for the send.
        return rpc.Blob({"size": n}, self.arena.view[base : base + n])

    # -- placement group bundles ---------------------------------------------

    async def _prepare_pg(self, conn, p):
        pg_id = p["pg_id"]
        total_demand = ResourceSet()
        for _, units in p["bundles"].items():
            total_demand = total_demand + ResourceSet.from_units(units)
        if not total_demand.is_subset_of(self.available):
            return {"success": False}
        self.available = self.available - total_demand
        self.pg_prepared[pg_id] = total_demand
        # Remember per-bundle layout for commit.
        self.pg_prepared_bundles = getattr(self, "pg_prepared_bundles", {})
        self.pg_prepared_bundles[pg_id] = p["bundles"]
        self._mark_dirty()
        return {"success": True}

    async def _commit_pg(self, conn, p):
        pg_id = p["pg_id"]
        base = self.pg_prepared.pop(pg_id, None)
        bundles = getattr(self, "pg_prepared_bundles", {}).pop(pg_id, None)
        if base is None or bundles is None:
            return {"ok": False}
        group_units: Dict[str, int] = {f"bundle_group_{pg_id}": len(bundles) * 10000}
        for idx, units in bundles.items():
            for k, v in units.items():
                group_units[f"{k}_group_{idx}_{pg_id}"] = v
                group_units[f"{k}_group_{pg_id}"] = (
                    group_units.get(f"{k}_group_{pg_id}", 0) + v
                )
        group = ResourceSet.from_units(group_units)
        self.total = self.total + group
        self.available = self.available + group
        self.pg_committed[pg_id] = (base, group)
        self._mark_dirty()
        self._try_grant_leases()
        return {"ok": True}

    async def _release_pg(self, conn, p):
        pg_id = p["pg_id"]
        if pg_id in self.pg_prepared:
            self.available = self.available + self.pg_prepared.pop(pg_id)
            getattr(self, "pg_prepared_bundles", {}).pop(pg_id, None)
        if pg_id in self.pg_committed:
            base, group = self.pg_committed.pop(pg_id)
            self.total = self.total - group
            self.available = self.available - group + base
            # Kill workers leased against this PG's resources.
            for lease_id, handle in list(self.leases.items()):
                demand = getattr(handle, "demand", None)
                if demand and any(pg_id in k for k in demand.keys()):
                    self._release_lease(lease_id, dirty=True)
        self._mark_dirty()
        return {"ok": True}

    async def _node_stats(self, conn, p):
        out = {
            "node_id": self.node_id,
            "total": self.total.to_units(),
            "available": self.available.to_units(),
            "num_workers": len(self.workers),
            "num_idle": len(self.idle_workers),
            "num_leases": len(self.leases),
            "store_used": self.store_used,
            "store_capacity": self.store_capacity,
            "num_objects": self.store.num_objects,
            "pending_leases": len(self.pending_leases) + len(self.infeasible_leases),
            "spilled_objects": len(self.spilled),
            "spilled_bytes": self.spilled_bytes,
            "pinned_objects": len(self.pinned_objects),
            "push_stats": dict(self.push_manager.stats),
            # Unmet demand shapes for the autoscaler's bin-packing
            # (reference: resource_demand_scheduler reads task demands).
            # Infeasible shapes first — they are the scale-up signal.
            "pending_demand": [
                req.demand.to_units()
                for req in (self.infeasible_leases + self.pending_leases)[:20]
            ],
        }
        # Detail payloads for the state API (reference: raylet
        # GetTasksInfo/GetObjectsInfo, node_manager.proto:424-426).
        if p.get("include_workers"):
            idle = {w.worker_id for w in self.idle_workers}
            out["workers"] = [
                {
                    "worker_id": w.worker_id,
                    "pid": getattr(w.proc, "pid", None),
                    "actor_id": w.actor_id,
                    "lease_id": w.lease_id,
                    "state": "IDLE" if w.worker_id in idle else "BUSY",
                    "node_id": self.node_id,
                }
                for w in self.workers.values()
            ]
        if p.get("include_objects"):
            objs = []
            for oid in list(self.obj_last_access):
                info = self.store.lookup(oid)
                if info is None:
                    continue
                objs.append(
                    {
                        "object_id": oid,
                        "size": info[1],
                        "sealed": info[2],
                        "pinned": info[3],
                        "node_id": self.node_id,
                    }
                )
            out["objects"] = objs
        return out


async def main() -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--session", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="")
    parser.add_argument("--object-store-memory", type=int, default=None)
    args = parser.parse_args()
    resources = None
    if args.resources:
        import json

        resources = json.loads(args.resources)
    raylet = Raylet(
        (args.gcs_host, args.gcs_port),
        args.session,
        host=args.host,
        port=args.port,
        resources=resources,
        object_store_memory=args.object_store_memory,
    )
    addr = await raylet.start()
    print(f"RAYLET_ADDR {addr[0]}:{addr[1]} NODE {raylet.node_id}", flush=True)
    await asyncio.Event().wait()


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    rpc.install_event_loop()
    asyncio.run(main())
