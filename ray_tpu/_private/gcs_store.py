"""GCS persistence: pluggable store clients.

TPU-native analog of the reference's StoreClient abstraction
(src/ray/gcs/store_client/store_client.h:33). Three backends, selected by
the ``gcs_persist_backend`` knob when a persist path is configured:

- ``memory`` (in_memory_store_client.h:31): no durability, state dies with
  the GCS process. Also the backend when no persist path is given.
- ``sqlite``: write-through rows in a WAL-mode sqlite file. Simple and
  battle-tested, but pays a full journal commit per record.
- ``wal`` (default): an append-only CRC-framed log with *group commit* —
  mutations from one event-loop tick coalesce into a single OS write (and,
  per the ``gcs_store_sync`` policy, a single fsync), so hot-path
  persistence stops paying per-record sync cost. Snapshot-based compaction
  bounds the log, and recovery truncates a torn tail (a record cut mid-
  append by a crash) instead of refusing to start. This is the moral
  analog of the reference's Redis AOF everysec policy behind
  RedisStoreClient (redis_store_client.h:33).

Durability contract (docs/fault_tolerance.md): a *process* crash (kill -9)
loses nothing that ``put`` returned for — buffered records are flushed to
the OS before the process dies, and page-cache writes survive process
death. An *OS/power* crash can lose the records since the last fsync:
under the default ``gcs_store_sync="batch"`` that is at most one loop tick
of mutations for the wal backend, and for sqlite (``synchronous=NORMAL``
under WAL) the commits since the last WAL checkpoint. ``"always"`` closes
that window at per-commit fsync cost; ``"off"`` never fsyncs.

All values are opaque bytes (the GCS msgpacks its own records). Table
layout follows the reference's gcs_table_storage.cc (one logical table per
domain: kv, actors, named, jobs, pgs).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import sqlite3
import struct
import threading
import time
import zlib
from typing import Dict, Optional

import msgpack

from ray_tpu._private import telemetry
from ray_tpu._private.common import config

_TEL_WRITE_S = telemetry.histogram(
    "gcs",
    "store_write_s",
    "store commit latency (one group-commit flush or sqlite commit)",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_WAL_BYTES = telemetry.counter(
    "gcs", "store_wal_bytes", "bytes appended to the GCS WAL"
)
_TEL_WAL_COMPACTIONS = telemetry.counter(
    "gcs", "store_wal_compactions", "WAL snapshot compactions"
)


class StoreClient:
    """Abstract synchronous KV-per-table store."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def get_all(self, table: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def crash(self) -> None:
        """Abrupt-death analog of close(): release OS resources without the
        graceful-shutdown work (checkpoint/compaction/fsync), preserving
        exactly what a killed process would leave on disk."""
        self.close()


class InMemoryStoreClient(StoreClient):
    """Default: no durability (reference in_memory_store_client.h:31)."""

    def __init__(self):
        self._tables: Dict[str, Dict[str, bytes]] = {}

    def put(self, table: str, key: str, value: bytes) -> None:
        self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key: str) -> Optional[bytes]:
        return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        self._tables.get(table, {}).pop(key, None)

    def get_all(self, table: str) -> Dict[str, bytes]:
        return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable file-backed store for GCS fault tolerance.

    WAL mode + one flat table; writes are a few hundred bytes each and run
    inline on the GCS loop (sub-ms on local disk, same order as the
    reference's Redis round trip from the GCS process).

    Sync policy (``gcs_store_sync``): "always" -> synchronous=FULL (fsync
    per commit), "batch" -> NORMAL (WAL writes fsynced at checkpoint; an
    OS crash can lose the last commits), "off" -> OFF. close() checkpoints
    the WAL (wal_checkpoint TRUNCATE) so a graceful shutdown leaves the
    main db file complete and the -wal file empty.
    """

    _SYNC_PRAGMA = {"always": "FULL", "batch": "NORMAL", "off": "OFF"}

    def __init__(self, path: str, sync: Optional[str] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        level = self._SYNC_PRAGMA.get(sync or config.gcs_store_sync, "NORMAL")
        self._db.execute(f"PRAGMA synchronous={level}")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key TEXT, value BLOB,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return  # shutdown race: a trailing handler after stop()
            t0 = time.perf_counter()
            self._db.execute(
                "INSERT OR REPLACE INTO gcs (tbl, key, value) VALUES (?, ?, ?)",
                (table, key, value),
            )
            self._db.commit()
            _TEL_WRITE_S.default.observe(time.perf_counter() - t0)

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            if self._closed:
                return None
            row = self._db.execute(
                "SELECT value FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._db.execute(
                "DELETE FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            )
            self._db.commit()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            if self._closed:
                return {}
            rows = self._db.execute(
                "SELECT key, value FROM gcs WHERE tbl = ?", (table,)
            ).fetchall()
        return {k: bytes(v) for k, v in rows}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                # Fold the -wal file back into the main db so a graceful
                # shutdown leaves one complete file (and no stale -wal to
                # replay — or to lose — on the next open).
                self._db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:
                pass
            self._db.close()

    def crash(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # No checkpoint: the -wal file stays behind exactly as a killed
            # process would leave it; sqlite replays it on the next open.
            self._db.close()


# -- WAL backend -------------------------------------------------------------

# Record framing: <u32 body_len> <u32 crc32(body)> <body>, body = msgpack
# [op, table, key, value]. Ops: "put", "del", and "snap" (value = packed
# {table: {key: value}} full state — a compaction checkpoint; replay resets
# to it and continues).
_HDR = struct.Struct("<II")


def _frame(op: str, table: str, key: str, value: Optional[bytes]) -> bytes:
    body = msgpack.packb([op, table, key, value], use_bin_type=True)
    return _HDR.pack(len(body), zlib.crc32(body)) + body


class WalStoreClient(StoreClient):
    """Append-only group-commit log (see module docstring).

    Reads are served from a full in-memory mirror; every mutation appends a
    frame to an in-process buffer and schedules one flush per event-loop
    tick (``loop.call_soon``), so N mutations in one handler burst cost one
    ``os.write`` + one fsync instead of N. Without a running loop (direct
    library use, tests) each mutation flushes inline.
    """

    def __init__(
        self,
        path: str,
        sync: Optional[str] = None,
        compact_bytes: Optional[int] = None,
    ):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._sync = sync or config.gcs_store_sync
        self._compact_bytes = (
            config.gcs_wal_compact_bytes if compact_bytes is None else compact_bytes
        )
        self._lock = threading.Lock()
        self._closed = False
        self._tables: Dict[str, Dict[str, bytes]] = {}
        self._pending: list = []
        self._flush_scheduled = False
        # Optional crash-point probe: called after each durable group commit
        # with (commit_index, log_byte_offset, n_ops). Used by the explorer
        # (devtools/explore.py) to snapshot acked state at every boundary.
        self.commit_listener = None
        self._commit_index = 0
        self._recover()
        self._fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        self._log_bytes = os.fstat(self._fd).st_size

    # -- recovery ------------------------------------------------------------

    def _recover(self) -> None:
        """Replay the log into the mirror; truncate at the first torn or
        corrupt record (a crash mid-append leaves a short header, a short
        body, or a body whose CRC does not match — everything before it is
        intact and everything after it was never acknowledged as flushed)."""
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            data = f.read()
        if data.startswith(b"SQLite format 3"):
            # Backend switched under an existing file: refuse rather than
            # "recover" a sqlite db into an empty log (torn-tail truncation
            # at offset 0 would destroy it).
            raise ValueError(
                f"{self._path} is a sqlite store; set gcs_persist_backend="
                "sqlite or remove the file"
            )
        off = 0
        good = 0
        while off + _HDR.size <= len(data):
            blen, crc = _HDR.unpack_from(data, off)
            body = data[off + _HDR.size : off + _HDR.size + blen]
            if len(body) < blen or zlib.crc32(body) != crc:
                break  # torn tail
            op, table, key, value = msgpack.unpackb(body, raw=False)
            if op == "snap":
                self._tables = {
                    t: dict(kv)
                    for t, kv in msgpack.unpackb(value, raw=False).items()
                }
            elif op == "put":
                self._tables.setdefault(table, {})[key] = value
            else:  # "del"
                self._tables.get(table, {}).pop(key, None)
            off += _HDR.size + blen
            good = off
        if good < len(data):
            with open(self._path, "r+b") as f:
                f.truncate(good)

    # -- group commit --------------------------------------------------------

    def _schedule_flush(self) -> None:
        if self._sync == "always":
            # Per-record durability: no group commit, fsync inline.
            self._flush()
            return
        if self._flush_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush()
            return
        self._flush_scheduled = True
        loop.call_soon(self.flush)

    def flush(self) -> None:
        """Write and (per sync policy) fsync all buffered frames: the group
        commit. Public so shutdown paths can force the tail out."""
        with self._lock:
            self._flush_scheduled = False
            self._flush()

    def _flush(self) -> None:  # caller holds _lock (or is single-threaded init)
        if not self._pending or self._closed:
            self._pending.clear()
            return
        n_ops = len(self._pending)
        buf = b"".join(self._pending)
        self._pending.clear()
        t0 = time.perf_counter()
        os.write(self._fd, buf)
        if self._sync != "off":
            os.fsync(self._fd)
        _TEL_WRITE_S.default.observe(time.perf_counter() - t0)
        _TEL_WAL_BYTES.default.inc(len(buf))
        self._log_bytes += len(buf)
        self._commit_index += 1
        if self.commit_listener is not None:
            self.commit_listener(self._commit_index, self._log_bytes, n_ops)
        if self._compact_bytes and self._log_bytes > self._compact_bytes:
            self._compact()

    def _compact(self) -> None:
        """Snapshot compaction: write the full mirror as one "snap" frame to
        a temp file and atomically rename it over the log. Readers of the
        old file (none — the GCS is the only client) and a crash at any
        point see either the old log or the complete snapshot."""
        snap = _frame(
            "snap",
            "",
            "",
            msgpack.packb(self._tables, use_bin_type=True),
        )
        tmp = self._path + ".compact"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, snap)
            if self._sync != "off":
                os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, self._path)
        os.close(self._fd)
        self._fd = os.open(self._path, os.O_WRONLY | os.O_APPEND)
        self._log_bytes = len(snap)
        _TEL_WAL_COMPACTIONS.default.inc()

    # -- StoreClient API -----------------------------------------------------

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return
            self._tables.setdefault(table, {})[key] = value
            self._pending.append(_frame("put", table, key, value))
            self._schedule_flush()

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._tables.get(table, {}).pop(key, None)
            self._pending.append(_frame("del", table, key, None))
            self._schedule_flush()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush()
            self._closed = True
            try:
                if self._sync != "off":
                    os.fsync(self._fd)
            except OSError:
                pass
            os.close(self._fd)

    def crash(self) -> None:
        """Kill -9 analog: the buffered tail reaches the OS (an in-process
        buffer is an artifact of the simulation — a real group-commit store
        writes before acking) but is NOT fsynced, and no compaction or
        checkpoint runs."""
        with self._lock:
            if self._closed:
                return
            buf = b"".join(self._pending)
            self._pending.clear()
            self._closed = True
            if buf:
                os.write(self._fd, buf)
                self._log_bytes += len(buf)
            os.close(self._fd)


# -- Replicated backend ------------------------------------------------------
#
# Same physical framing as the wal backend, but the body grows to
# [op, table, key, value, term, seq]:
#
# - ``term`` is the writer's leadership term. Every member tracks the
#   highest term it has ever accepted (its *fence*); an append from an
#   older term raises StaleLeaderError instead of landing — the mechanism
#   that stops a deposed, partitioned primary from split-braining the
#   actor/PG tables after a standby promoted (reference: Redis
#   replication + the GCS's "who is leader" record; Raft's term check in
#   miniature).
# - ``seq`` is the writer's monotonic log position, identical across
#   members (every member receives the same stream), used to pick the
#   freshest member on open and to bring stale members up via a snapshot
#   frame ("snap" carries the full tables plus the term/seq watermark).
#
# A replication *group* is one primary log plus N follower logs (default
# paths ``<path>.follower<i>``), each modeling an independent store
# process on another host. A group commit acks once a *majority* of
# members — ⌈(n+1)/2⌉, the leader's own append included — have the frame
# durable under the ``gcs_store_sync`` contract. Laggards (a slow or
# partitioned minority) catch up asynchronously: each follower has its own
# serial ship lane, and a member whose applied ``seq`` fell behind the
# stream receives the full state as one snapshot frame instead of the
# incremental buffer. Losing or partitioning a minority therefore never
# stalls the commit path; losing a majority demotes the leader (it fences
# itself rather than acking writes no quorum holds).
#
# The election on open mirrors Raft's: it requires a *majority* of members
# reachable and adopts the highest (term, seq) among them. Any ack quorum
# intersects any election majority, so every acknowledged record is seen
# by — and adopted into — the new leader's log, even when the single
# freshest *file* belongs to an unreachable member.


def _parse_replicated(data: bytes):
    """Replay a replicated-format log: returns (tables, term, seq,
    good_offset). Torn/corrupt tails stop the replay exactly like the wal
    backend; legacy 4-field frames are accepted with term=0/seq untouched
    so a plain wal file can be adopted into a group."""
    tables: Dict[str, Dict[str, bytes]] = {}
    term = 0
    seq = 0
    off = 0
    good = 0
    while off + _HDR.size <= len(data):
        blen, crc = _HDR.unpack_from(data, off)
        body = data[off + _HDR.size : off + _HDR.size + blen]
        if len(body) < blen or zlib.crc32(body) != crc:
            break
        fields = msgpack.unpackb(body, raw=False)
        op, table, key, value = fields[:4]
        if len(fields) >= 6:
            term = max(term, fields[4])
            seq = max(seq, fields[5])
        if op == "snap":
            tables = {
                t: dict(kv)
                for t, kv in msgpack.unpackb(value, raw=False).items()
            }
        elif op == "put":
            tables.setdefault(table, {})[key] = value
        else:
            tables.get(table, {}).pop(key, None)
        off += _HDR.size + blen
        good = off
    return tables, term, seq, good


def apply_replicated(tables: Dict[str, Dict[str, bytes]], data: bytes):
    """Splice replicated frames over a live mirror — frame by frame so
    deletes stay correct and a "snap" frame replaces the whole state.
    Returns (tables, term, seq, good): the (possibly replaced) mirror
    dict, the max term/seq seen, and how many bytes formed whole valid
    frames (a torn tail stops the splice, as in _parse_replicated).
    Shared by ReplicaTailer (file mode) and the RPC-fed standby mirror."""
    term = 0
    seq = 0
    _, _, _, good = _parse_replicated(data)
    off = 0
    while off < good:
        blen, _ = _HDR.unpack_from(data, off)
        body = data[off + _HDR.size : off + _HDR.size + blen]
        fields = msgpack.unpackb(body, raw=False)
        op, table, key, value = fields[:4]
        if len(fields) >= 6:
            term = max(term, fields[4])
            seq = max(seq, fields[5])
        if op == "snap":
            tables = {
                t: dict(kv)
                for t, kv in msgpack.unpackb(value, raw=False).items()
            }
        elif op == "put":
            tables.setdefault(table, {})[key] = value
        else:
            tables.get(table, {}).pop(key, None)
        off += _HDR.size + blen
    return tables, term, seq, good


def _rframe(op, table, key, value, term, seq) -> bytes:
    body = msgpack.packb(
        [op, table, key, value, term, seq], use_bin_type=True
    )
    return _HDR.pack(len(body), zlib.crc32(body)) + body


class _ReplicaLog:
    """One member of a replication group: an append-only log file plus the
    fence state a real follower process would hold. Instances are shared
    in-process through a registry keyed by path, so a deposed leader's
    store client and the promoted leader's client hit the *same* fence —
    the in-process model of a follower rejecting a stale leader's
    shipped stream."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        self._refs = 0
        data = b""
        if os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
        _, term, seq, good = _parse_replicated(data)
        if good < len(data):
            with open(path, "r+b") as f:
                f.truncate(good)
        self.fence_term = term
        self.term = term
        self.seq = seq
        self._fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        self.log_bytes = good

    def raise_fence(self, term: int) -> None:
        """Adopt ``term`` as the minimum acceptable leader term. Called on
        open/promotion so a new leader fences the old one before its
        first write, not after. A partitioned member cannot receive the
        fence — it is fenced on rejoin by the catch-up snapshot instead."""
        if self.path in _PARTITIONED:
            return
        with self._lock:
            if term > self.fence_term:
                self.fence_term = term
                _FENCE_GEN[0] += 1

    def append(self, buf: bytes, term: int, seq: int, sync: str) -> None:
        """Accept one shipped group-commit from leader ``term`` ending at
        ``seq``; reject stale terms with StaleLeaderError."""
        from ray_tpu._private.rpc import StaleLeaderError  # lazy: no cycle at import

        if self.path in _PARTITIONED:
            raise ReplicaUnreachableError(
                f"replica {os.path.basename(self.path)} unreachable (partitioned)"
            )
        with self._lock:
            if term < self.fence_term:
                raise StaleLeaderError(
                    f"append from term {term} rejected by "
                    f"replica {os.path.basename(self.path)} "
                    f"(fence at term {self.fence_term})"
                )
            if term > self.fence_term:
                self.fence_term = term
                _FENCE_GEN[0] += 1
            os.write(self._fd, buf)
            if sync != "off":
                os.fsync(self._fd)
            self.term = term
            self.seq = seq
            self.log_bytes += len(buf)

    def reset_with(self, snap: bytes, term: int, seq: int, sync: str) -> None:
        """Replace the whole log with one snapshot frame (compaction, and
        catch-up of a stale member): temp file + atomic rename, same
        crash-safety argument as WalStoreClient._compact. Fenced exactly
        like append: a deposed leader must not be able to "catch up" a
        member that a newer term already fenced — that would replace the
        new leader's state wholesale (split-brain through compaction)."""
        from ray_tpu._private.rpc import StaleLeaderError  # lazy: no cycle at import

        if self.path in _PARTITIONED:
            raise ReplicaUnreachableError(
                f"replica {os.path.basename(self.path)} unreachable (partitioned)"
            )
        with self._lock:
            if term < self.fence_term:
                raise StaleLeaderError(
                    f"catch-up snapshot from term {term} rejected by "
                    f"replica {os.path.basename(self.path)} "
                    f"(fence at term {self.fence_term})"
                )
            tmp = self.path + ".compact"
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                os.write(fd, snap)
                if sync != "off":
                    os.fsync(fd)
            finally:
                os.close(fd)
            os.rename(tmp, self.path)
            os.close(self._fd)
            self._fd = os.open(self.path, os.O_WRONLY | os.O_APPEND)
            self.term = term
            self.seq = seq
            if term > self.fence_term:
                self.fence_term = term
                _FENCE_GEN[0] += 1
            self.log_bytes = len(snap)

    def write_unsynced(self, buf: bytes) -> None:
        """crash() path: the buffered tail reaches the OS, no fsync."""
        if self.path in _PARTITIONED:
            return  # a dying leader cannot reach a partitioned member either
        with self._lock:
            try:
                os.write(self._fd, buf)
                self.log_bytes += len(buf)
            except OSError:
                pass

    # registry refcounting: the fd stays open while any client holds the
    # replica; the last release closes it and drops the registry entry.

    def _acquire(self) -> None:
        self._refs += 1

    def _release(self) -> None:
        self._refs -= 1
        if self._refs <= 0:
            with self._lock:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
            with _REGISTRY_LOCK:
                if _REPLICAS.get(self.path) is self:
                    del _REPLICAS[self.path]


_REPLICAS: Dict[str, "_ReplicaLog"] = {}
_REGISTRY_LOCK = threading.Lock()
# Global fence generation: bumped whenever ANY member's fence rises, so
# client put/delete can skip the per-write max() over members (the hot
# path of every GCS mutation) and only re-derive the fence after a bump.
_FENCE_GEN = [0]


def _open_replica(path: str) -> _ReplicaLog:
    path = os.path.abspath(path)
    with _REGISTRY_LOCK:
        rep = _REPLICAS.get(path)
        if rep is not None and not os.path.exists(path):
            # The host died under the live handle (file destroyed): the
            # registry entry models a process that no longer exists.
            del _REPLICAS[path]
            rep = None
        if rep is None:
            rep = _ReplicaLog(path)
            _REPLICAS[path] = rep
        rep._acquire()
        return rep


def follower_paths(path: str, n: Optional[int] = None) -> list:
    """Default follower log paths for a replication group rooted at
    ``path`` (one per simulated follower store host)."""
    if n is None:
        n = max(1, int(config.gcs_replication_followers))
    return [f"{path}.follower{i}" for i in range(n)]


def drop_host(path: str) -> list:
    """Machine-loss analog for chaos: destroy the primary member's file
    and its in-process replica object (process + disk gone). Follower
    members are untouched. Returns the paths removed."""
    path = os.path.abspath(path)
    removed = []
    with _REGISTRY_LOCK:
        rep = _REPLICAS.pop(path, None)
    if rep is not None:
        try:
            os.close(rep._fd)
        except OSError:
            pass
    if os.path.exists(path):
        os.unlink(path)
        removed.append(path)
    return removed


class ReplicaUnreachableError(OSError):
    """A shipped append/snapshot could not be delivered because the member
    host is network-partitioned from the leader (chaos/explorer fault).
    Fail-fast and deterministic: the member votes nothing toward the ack
    quorum and its lag grows until the partition heals."""


class QuorumLostError(RuntimeError):
    """Fewer than a majority of replication-group members are reachable:
    no election may be held (an ack quorum might hide entirely inside the
    unreachable set) and no leader may commit."""


# Network-partition fault injection: a partitioned member host is
# unreachable from everyone — appends, snapshot catch-up, and fence raises
# all fail fast with ReplicaUnreachableError, and elections must not count
# it toward the reachable majority. Keyed by abspath, like _REPLICAS.
_PARTITIONED: set = set()


def partition_host(path: str) -> str:
    """Partition one member host away from the group (chaos nemesis /
    explorer fault). Returns the normalized path for heal_host."""
    path = os.path.abspath(path)
    _PARTITIONED.add(path)
    return path


def heal_host(path: str) -> None:
    _PARTITIONED.discard(os.path.abspath(path))


def heal_all_partitions() -> None:
    """Chaos per-seed hygiene: drop every injected partition."""
    _PARTITIONED.clear()


def partitioned_hosts() -> set:
    return set(_PARTITIONED)


# Election claim registry: standbys racing a promotion claim their target
# term here atomically; only the highest claim proceeds to open the store.
# In-process analog of a Raft RequestVote round — cross-process safety
# still rests on the durable fence frames (an open at or below a durable
# fence raises StaleLeaderError on the first write).
_TERM_CLAIMS: Dict[str, int] = {}


def try_claim_term(path: str, term: int) -> bool:
    """Atomically claim leadership ``term`` for the group rooted at
    ``path``. Returns False if an equal-or-higher claim exists (another
    standby won this round — re-enter the watch loop at the new term)."""
    path = os.path.abspath(path)
    with _REGISTRY_LOCK:
        if _TERM_CLAIMS.get(path, 0) >= term:
            return False
        _TERM_CLAIMS[path] = term
        return True


_TEL_REPL_LAG_S = telemetry.histogram(
    "gcs",
    "replication_lag_s",
    "follower ack latency per shipped group-commit",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_REPL_LAG_SEQ = telemetry.gauge(
    "gcs",
    "replica_lag_seq",
    "per-member replication lag: leader seq minus the member's applied seq",
)
_TEL_QUORUM_SIZE = telemetry.gauge(
    "gcs",
    "quorum_size",
    "ack quorum of the replication group: ⌈(members+1)/2⌉",
)
_TEL_QUORUM_WAIT_S = telemetry.histogram(
    "gcs",
    "commit_quorum_wait_s",
    "group-commit wait from first member append to quorum ack",
    buckets=telemetry.LATENCY_BUCKETS_S,
)


class ReplicatedStoreClient(StoreClient):
    """WAL chained with majority-quorum log-shipping to follower members
    (see the replicated-backend comment above). Keeps WalStoreClient's
    group commit: mutations from one event-loop tick coalesce into one
    buffer that is appended — and per ``gcs_store_sync`` fsynced — on a
    *majority* of members (leader included) before the flush acks.
    Laggard members catch up asynchronously on their own serial ship
    lanes; a two-member group degenerates to wait-for-all (quorum 2 of 2),
    preserving the original synchronous-shipping semantics.

    Leadership: the client carries the writer's ``term``. ``set_term``
    raises the fence on every reachable member (promotion); a put/delete
    under a term older than any member's fence raises StaleLeaderError
    without touching the mirror, and a fence raised mid-tick poisons the
    client (``fenced``) so the deposed leader stops cleanly. Losing a
    reachable majority mid-flight fences the client the same way — the
    leader demotes rather than acking unreplicated writes.
    """

    def __init__(
        self,
        path: str,
        followers: Optional[list] = None,
        term: Optional[int] = None,
        sync: Optional[str] = None,
        compact_bytes: Optional[int] = None,
        on_fenced=None,
    ):
        self._path = os.path.abspath(path)
        self._sync = sync or config.gcs_store_sync
        self._compact_bytes = (
            config.gcs_wal_compact_bytes if compact_bytes is None else compact_bytes
        )
        self._lock = threading.Lock()
        self._closed = False
        self.fenced = False
        self._fence_gen = -1  # forces a full fence check on first write
        self._on_fenced = on_fenced
        self._pending: list = []
        self._flush_scheduled = False
        # Optional crash-point probe: called after each quorum-acked group
        # commit with (seq, n_ops). Fence aborts never ack, so never fire
        # it (see devtools/explore.py crash enumeration).
        self.commit_listener = None
        # Optional stream hook for the RPC-fed standby: called after each
        # quorum ack with (frames, term, seq, prev_seq) — the raw shipped
        # bytes plus the watermark they start after (gap detection).
        self.ship_listener = None
        member_paths = [self._path] + [
            os.path.abspath(p)
            for p in (followers if followers is not None else follower_paths(path))
        ]
        self._members = [_open_replica(p) for p in member_paths]
        self._quorum = len(self._members) // 2 + 1
        # Per-follower serial ship lanes: one single-thread executor per
        # follower so member fsyncs overlap (os.fsync drops the GIL) while
        # each member still applies its stream in order — required now that
        # a laggard's append may still be in flight when the next group
        # commit acks on the quorum.
        self._ship_lanes = [
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"gcs-repl-ship-{i}"
            )
            for i in range(1, len(self._members))
        ]
        # Election (Raft-style): require a majority of members reachable,
        # then adopt the highest (term, seq) among the *reachable* set.
        # Any ack quorum intersects any reachable majority, so every
        # acknowledged record is present in the adopted log — even when
        # the single freshest file sits on a partitioned member. After
        # machine loss of the primary a fresh primary file starts at
        # (term 0, seq 0) and loses this election.
        reachable = [
            i for i, m in enumerate(self._members) if m.path not in _PARTITIONED
        ]
        if len(reachable) < self._quorum:
            self.close()
            raise QuorumLostError(
                f"only {len(reachable)} of {len(self._members)} replication "
                f"members reachable; need a majority of {self._quorum} to elect"
            )
        states = {}
        for i in reachable:
            m = self._members[i]
            data = b""
            if os.path.exists(m.path):
                with open(m.path, "rb") as f:
                    data = f.read()
            states[i] = _parse_replicated(data)
        best = max(reachable, key=lambda i: (states[i][1], states[i][2]))
        tables, bterm, bseq, _ = states[best]
        self._tables = tables
        self._seq = bseq
        self._term = bterm if term is None else term
        fence = max(self._members[i].fence_term for i in reachable)
        if self._term < fence:
            from ray_tpu._private.rpc import StaleLeaderError

            self.close()
            raise StaleLeaderError(
                f"store opened at term {self._term} behind "
                f"fence {fence}"
            )
        # Catch-up: stale reachable members (lost host replaced, follower
        # behind) receive the full state as one snapshot frame, then ride
        # the tail. Partitioned members catch up the same way when their
        # lag is noticed after the partition heals.
        snap = None
        for i in reachable:
            if states[i][2] < bseq or states[i][1] < bterm:
                if snap is None:
                    snap = _rframe(
                        "snap", "", "",
                        msgpack.packb(self._tables, use_bin_type=True),
                        self._term, self._seq,
                    )
                self._members[i].reset_with(snap, self._term, self._seq, self._sync)
        for i in reachable:
            self._members[i].raise_fence(self._term)
        # Per-follower shipped watermark: the seq after the last frame
        # SUBMITTED to the member's lane. Laggard detection keys off this,
        # not the member's applied seq — an in-flight append on a lane is
        # ordered, not behind, and must not trigger a snapshot re-ship.
        # Partitioned members keep their stale applied seq here, so their
        # first post-heal flush mismatches and ships the catch-up snapshot.
        self._shipped = [m.seq for m in self._members[1:]]
        _TEL_QUORUM_SIZE.default.set(self._quorum)

    @property
    def term(self) -> int:
        return self._term

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def quorum(self) -> int:
        """Ack quorum: ⌈(members+1)/2⌉, the leader's own append included."""
        return self._quorum

    def replica_lag(self) -> Dict[str, int]:
        """Per-member replication lag in sequence numbers (leader seq minus
        the member's applied seq). 0 = fully caught up; the leader's own
        entry is always 0 by the time a commit acks."""
        return {
            os.path.basename(m.path): max(0, self._seq - m.seq)
            for m in self._members
        }

    def wait_replication(self) -> None:
        """Barrier: block until every in-flight follower ship (including
        laggard catch-up) has drained. Test/scan hook — the commit path
        never waits for more than the quorum."""
        if self._closed:
            return
        futs = [lane.submit(lambda: None) for lane in self._ship_lanes]
        for fut in futs:
            fut.result()

    def snapshot_tables(self):
        """Full state as (packed_tables, term, seq) — the ShipSnapshot RPC
        body for bootstrapping a cross-process standby mirror."""
        with self._lock:
            return (
                msgpack.packb(self._tables, use_bin_type=True),
                self._term,
                self._seq,
            )

    def set_term(self, term: int) -> None:
        """Adopt a (higher) leadership term and fence every reachable
        member at it: the promoted standby's first store act, before any
        write. Partitioned members are fenced on rejoin by catch-up."""
        from ray_tpu._private.rpc import StaleLeaderError

        with self._lock:
            fence = max(
                (
                    m.fence_term
                    for m in self._members
                    if m.path not in _PARTITIONED
                ),
                default=0,
            )
            if term < fence:
                raise StaleLeaderError(
                    f"cannot adopt term {term} behind "
                    f"fence {fence}"
                )
            self._term = term
        for m in self._members:
            m.raise_fence(term)

    def _check_fence(self) -> None:
        from ray_tpu._private.rpc import StaleLeaderError

        if self.fenced:
            raise StaleLeaderError(
                f"store client (term {self._term}) is fenced"
            )
        # Snapshot the generation BEFORE reading fences: a concurrent raise
        # leaves the stored generation stale, forcing a re-check next write.
        gen = _FENCE_GEN[0]
        fence = max(m.fence_term for m in self._members)
        if self._term < fence:
            self._mark_fenced()
            raise StaleLeaderError(
                f"write from term {self._term} rejected "
                f"(leadership fence at term {fence})"
            )
        self._fence_gen = gen

    def _mark_fenced(self) -> None:
        self.fenced = True
        self._pending.clear()
        if self._on_fenced is not None:
            cb, self._on_fenced = self._on_fenced, None
            try:
                cb()
            except Exception:
                pass

    # -- group commit (shipped) ---------------------------------------------

    def _schedule_flush(self) -> None:
        if self._sync == "always":
            self._flush()
            return
        if self._flush_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush()
            return
        self._flush_scheduled = True
        loop.call_soon(self.flush)

    def flush(self) -> None:
        with self._lock:
            self._flush_scheduled = False
            self._flush()

    def _ship_one(self, fi: int, m: "_ReplicaLog", buf, term, seq, prev_seq, snap) -> str:
        """Deliver one group commit to one follower on its serial lane.
        ``snap`` non-None means the member was behind the stream at submit
        time: ship the full state at (term, seq) instead of the
        incremental buffer (also how a healed partition rejoins — the
        snapshot carries the fence bump). Any failure (and any append that
        would land after a failed predecessor, leaving a gap in the
        member's log) demotes the shipped watermark to -1 so the next
        group commit re-ships the full state; the member never applies an
        out-of-order frame, so it can at worst be stale, never torn."""
        from ray_tpu._private.rpc import StaleLeaderError

        try:
            if snap is not None:
                m.reset_with(snap, term, seq, self._sync)
            else:
                if m.seq != prev_seq:
                    self._shipped[fi] = -1
                    return "resync"
                m.append(buf, term, seq, self._sync)
            return "ok"
        except ReplicaUnreachableError:
            self._shipped[fi] = -1
            return "unreachable"
        except StaleLeaderError:
            return "fenced"
        except OSError:
            self._shipped[fi] = -1
            return "error"

    def _flush(self) -> None:  # caller holds _lock
        from ray_tpu._private.rpc import StaleLeaderError

        if not self._pending or self._closed or self.fenced:
            self._pending.clear()
            return
        n_ops = len(self._pending)
        buf = b"".join(self._pending)
        self._pending.clear()
        prev_seq = self._seq - n_ops  # watermark the buffer starts after
        t0 = time.perf_counter()
        # Leader's own append is the first quorum vote.
        try:
            self._members[0].append(buf, self._term, self._seq, self._sync)
        except StaleLeaderError:
            # Fenced mid-tick: this tick's writes were never replicated and
            # the leadership that acknowledged them is over — the deposed
            # leader must stop serving, not limp on with a diverged mirror.
            self._mark_fenced()
            return
        # Ship to each follower on its serial lane. A member whose shipped
        # watermark is behind the stream (healed partition, failed ship,
        # reset file) gets the full state as one snapshot frame instead —
        # idempotent, and it truncates any unacked garbage the member may
        # carry. In-flight lane work does NOT count as behind: the lane
        # applies its stream in order.
        snap = None
        futs = []
        for fi, m in enumerate(self._members[1:]):
            if m.path in _PARTITIONED:
                continue  # fail-fast: no vote, lag accrues until heal
            this_snap = None
            if self._shipped[fi] != prev_seq:
                if snap is None:
                    snap = _rframe(
                        "snap", "", "",
                        msgpack.packb(self._tables, use_bin_type=True),
                        self._term, self._seq,
                    )
                this_snap = snap
            self._shipped[fi] = self._seq
            futs.append(
                self._ship_lanes[fi].submit(
                    self._ship_one, fi, m, buf, self._term, self._seq,
                    prev_seq, this_snap,
                )
            )
        # Quorum tally: ack as soon as a majority (leader included) holds
        # the commit. Laggard futures keep running on their lanes; their
        # lag is visible through replica_lag()/the replica_lag_seq gauge.
        needed = self._quorum - 1
        acks = 0
        saw_fence = False
        pending = set(futs)
        while pending and acks < needed and not saw_fence:
            done, pending = concurrent.futures.wait(
                pending, return_when=concurrent.futures.FIRST_COMPLETED
            )
            for fut in done:
                verdict = fut.result()
                if verdict == "ok":
                    acks += 1
                elif verdict == "fenced":
                    saw_fence = True
        if acks < needed:
            # No majority holds this commit: a newer leader fenced us, or
            # a majority of members is gone/partitioned. Either way the
            # leader demotes (fences itself) rather than acking writes no
            # quorum can recover.
            self._mark_fenced()
            return
        dt = time.perf_counter() - t0
        _TEL_WRITE_S.default.observe(dt)
        _TEL_REPL_LAG_S.default.observe(dt)
        _TEL_QUORUM_WAIT_S.default.observe(dt)
        _TEL_WAL_BYTES.default.inc(len(buf))
        for m in self._members[1:]:
            _TEL_REPL_LAG_SEQ.cell(member=os.path.basename(m.path)).set(
                max(0, self._seq - m.seq)
            )
        if self.commit_listener is not None:
            self.commit_listener(self._seq, n_ops)
        if self.ship_listener is not None:
            self.ship_listener(buf, self._term, self._seq, prev_seq)
        if self._compact_bytes and self._members[0].log_bytes > self._compact_bytes:
            snap = _rframe(
                "snap", "", "",
                msgpack.packb(self._tables, use_bin_type=True),
                self._term, self._seq,
            )
            try:
                self._members[0].reset_with(snap, self._term, self._seq, self._sync)
            except StaleLeaderError:
                # Fenced after the ack: the commit stands (a quorum holds
                # it), but this leadership is over — demote, skip compaction.
                self._mark_fenced()
                return
            # Follower resets ride their serial lanes so they cannot
            # reorder against an in-flight laggard append.
            for i, m in enumerate(self._members[1:]):
                if m.path in _PARTITIONED:
                    continue  # healed members catch up via the lag snapshot
                self._ship_lanes[i].submit(
                    self._ship_one, i, m, b"", self._term, self._seq,
                    self._seq, snap,
                )
            _TEL_WAL_COMPACTIONS.default.inc()

    # -- StoreClient API -----------------------------------------------------

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return
            if self.fenced or self._fence_gen != _FENCE_GEN[0]:
                self._check_fence()
            self._seq += 1
            self._tables.setdefault(table, {})[key] = value
            body = msgpack.packb(
                ["put", table, key, value, self._term, self._seq],
                use_bin_type=True,
            )
            self._pending.append(
                _HDR.pack(len(body), zlib.crc32(body)) + body
            )
            self._schedule_flush()

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            if self.fenced or self._fence_gen != _FENCE_GEN[0]:
                self._check_fence()
            self._seq += 1
            self._tables.get(table, {}).pop(key, None)
            body = msgpack.packb(
                ["del", table, key, None, self._term, self._seq],
                use_bin_type=True,
            )
            self._pending.append(
                _HDR.pack(len(body), zlib.crc32(body)) + body
            )
            self._schedule_flush()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush()
            self._closed = True
        for lane in self._ship_lanes:
            lane.shutdown(wait=True)  # drain laggard catch-up before release
        for m in self._members:
            m._release()

    def crash(self) -> None:
        """Process-death analog: the buffered tick reaches every reachable
        member's file (no fsync) — what a real leader that writes-before-
        acking would have already shipped."""
        with self._lock:
            if self._closed:
                return
            buf = b"" if self.fenced else b"".join(self._pending)
            self._pending.clear()
            self._closed = True
        for lane in self._ship_lanes:
            lane.shutdown(wait=False, cancel_futures=True)
        if buf:
            for m in self._members:
                m.write_unsynced(buf)
        for m in self._members:
            m._release()


class ReplicaTailer:
    """Warm-standby's view of a shipped log: re-reads new frames from a
    member file on each poll and applies them to a local mirror — the
    cross-process analog of a follower applying its received stream.
    Detects compaction/catch-up rewrites (inode change, shrink, or changed
    leading bytes — inode numbers alone are unreliable: many filesystems
    hand a renamed-over file the number the original just freed) and
    replays from offset zero."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self.tables: Dict[str, Dict[str, bytes]] = {}
        self.term = 0
        self.seq = 0
        self._off = 0
        self._ino = None
        self._head = b""  # first bytes at last reset: rewrite fingerprint

    def poll(self) -> int:
        """Apply any new frames; returns how many bytes were consumed."""
        try:
            st = os.stat(self.path)
        except OSError:
            return 0
        try:
            with open(self.path, "rb") as f:
                head = f.read(len(self._head)) if self._head else b""
        except OSError:
            return 0
        if (
            st.st_ino != self._ino
            or st.st_size < self._off
            or head != self._head
        ):
            self._ino = st.st_ino
            self._off = 0
            self.tables = {}
        if st.st_size <= self._off:
            return 0
        with open(self.path, "rb") as f:
            f.seek(self._off)
            data = f.read()
        if self._off == 0:
            self._head = data[:32]
        self.tables, term, seq, good = apply_replicated(self.tables, data)
        if good == 0:
            return 0
        self.term = max(self.term, term)
        self.seq = max(self.seq, seq)
        self._off += good
        return good

    def get(self, table: str, key: str) -> Optional[bytes]:
        return self.tables.get(table, {}).get(key)

    def get_all(self, table: str) -> Dict[str, bytes]:
        return dict(self.tables.get(table, {}))


def inject_torn_tail(path: str) -> bool:
    """Append a partial frame to a WAL file — the on-disk shape of a crash
    that died mid-append of a NEW record (its header landed, its body did
    not). Recovery must truncate it without losing any earlier record.
    Returns False (no-op) for non-WAL persistence files (sqlite)."""
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        head = f.read(16)
    if head[:16].startswith(b"SQLite format 3"):
        return False
    with open(path, "ab") as f:
        f.write(_HDR.pack(512, 0xDEADBEEF) + b"\x00" * 17)  # 512-byte body cut short
    return True


def make_store(
    persist_path: Optional[str],
    backend: Optional[str] = None,
    term: Optional[int] = None,
    on_fenced=None,
) -> StoreClient:
    """Build the configured store. No path -> in-memory regardless of
    backend; with a path, ``backend`` (default: the ``gcs_persist_backend``
    knob) picks wal / sqlite / memory / replicated. ``term``/``on_fenced``
    apply to the replicated backend only (leadership stamp + fencing
    notification for the HA control plane)."""
    if not persist_path:
        return InMemoryStoreClient()
    backend = backend or config.gcs_persist_backend
    if backend == "sqlite":
        return SqliteStoreClient(persist_path)
    if backend == "memory":
        return InMemoryStoreClient()
    if backend == "replicated":
        return ReplicatedStoreClient(persist_path, term=term, on_fenced=on_fenced)
    if backend != "wal":
        raise ValueError(f"unknown gcs_persist_backend {backend!r}")
    return WalStoreClient(persist_path)
