"""GCS persistence: pluggable store clients.

TPU-native analog of the reference's StoreClient abstraction
(src/ray/gcs/store_client/store_client.h:33). Three backends, selected by
the ``gcs_persist_backend`` knob when a persist path is configured:

- ``memory`` (in_memory_store_client.h:31): no durability, state dies with
  the GCS process. Also the backend when no persist path is given.
- ``sqlite``: write-through rows in a WAL-mode sqlite file. Simple and
  battle-tested, but pays a full journal commit per record.
- ``wal`` (default): an append-only CRC-framed log with *group commit* —
  mutations from one event-loop tick coalesce into a single OS write (and,
  per the ``gcs_store_sync`` policy, a single fsync), so hot-path
  persistence stops paying per-record sync cost. Snapshot-based compaction
  bounds the log, and recovery truncates a torn tail (a record cut mid-
  append by a crash) instead of refusing to start. This is the moral
  analog of the reference's Redis AOF everysec policy behind
  RedisStoreClient (redis_store_client.h:33).

Durability contract (docs/fault_tolerance.md): a *process* crash (kill -9)
loses nothing that ``put`` returned for — buffered records are flushed to
the OS before the process dies, and page-cache writes survive process
death. An *OS/power* crash can lose the records since the last fsync:
under the default ``gcs_store_sync="batch"`` that is at most one loop tick
of mutations for the wal backend, and for sqlite (``synchronous=NORMAL``
under WAL) the commits since the last WAL checkpoint. ``"always"`` closes
that window at per-commit fsync cost; ``"off"`` never fsyncs.

All values are opaque bytes (the GCS msgpacks its own records). Table
layout follows the reference's gcs_table_storage.cc (one logical table per
domain: kv, actors, named, jobs, pgs).
"""

from __future__ import annotations

import asyncio
import os
import sqlite3
import struct
import threading
import time
import zlib
from typing import Dict, Optional

import msgpack

from ray_tpu._private import telemetry
from ray_tpu._private.common import config

_TEL_WRITE_S = telemetry.histogram(
    "gcs",
    "store_write_s",
    "store commit latency (one group-commit flush or sqlite commit)",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_WAL_BYTES = telemetry.counter(
    "gcs", "store_wal_bytes", "bytes appended to the GCS WAL"
)
_TEL_WAL_COMPACTIONS = telemetry.counter(
    "gcs", "store_wal_compactions", "WAL snapshot compactions"
)


class StoreClient:
    """Abstract synchronous KV-per-table store."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def get_all(self, table: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def crash(self) -> None:
        """Abrupt-death analog of close(): release OS resources without the
        graceful-shutdown work (checkpoint/compaction/fsync), preserving
        exactly what a killed process would leave on disk."""
        self.close()


class InMemoryStoreClient(StoreClient):
    """Default: no durability (reference in_memory_store_client.h:31)."""

    def __init__(self):
        self._tables: Dict[str, Dict[str, bytes]] = {}

    def put(self, table: str, key: str, value: bytes) -> None:
        self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key: str) -> Optional[bytes]:
        return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        self._tables.get(table, {}).pop(key, None)

    def get_all(self, table: str) -> Dict[str, bytes]:
        return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable file-backed store for GCS fault tolerance.

    WAL mode + one flat table; writes are a few hundred bytes each and run
    inline on the GCS loop (sub-ms on local disk, same order as the
    reference's Redis round trip from the GCS process).

    Sync policy (``gcs_store_sync``): "always" -> synchronous=FULL (fsync
    per commit), "batch" -> NORMAL (WAL writes fsynced at checkpoint; an
    OS crash can lose the last commits), "off" -> OFF. close() checkpoints
    the WAL (wal_checkpoint TRUNCATE) so a graceful shutdown leaves the
    main db file complete and the -wal file empty.
    """

    _SYNC_PRAGMA = {"always": "FULL", "batch": "NORMAL", "off": "OFF"}

    def __init__(self, path: str, sync: Optional[str] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        level = self._SYNC_PRAGMA.get(sync or config.gcs_store_sync, "NORMAL")
        self._db.execute(f"PRAGMA synchronous={level}")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key TEXT, value BLOB,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return  # shutdown race: a trailing handler after stop()
            t0 = time.perf_counter()
            self._db.execute(
                "INSERT OR REPLACE INTO gcs (tbl, key, value) VALUES (?, ?, ?)",
                (table, key, value),
            )
            self._db.commit()
            _TEL_WRITE_S.default.observe(time.perf_counter() - t0)

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            if self._closed:
                return None
            row = self._db.execute(
                "SELECT value FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._db.execute(
                "DELETE FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            )
            self._db.commit()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            if self._closed:
                return {}
            rows = self._db.execute(
                "SELECT key, value FROM gcs WHERE tbl = ?", (table,)
            ).fetchall()
        return {k: bytes(v) for k, v in rows}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                # Fold the -wal file back into the main db so a graceful
                # shutdown leaves one complete file (and no stale -wal to
                # replay — or to lose — on the next open).
                self._db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:
                pass
            self._db.close()

    def crash(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # No checkpoint: the -wal file stays behind exactly as a killed
            # process would leave it; sqlite replays it on the next open.
            self._db.close()


# -- WAL backend -------------------------------------------------------------

# Record framing: <u32 body_len> <u32 crc32(body)> <body>, body = msgpack
# [op, table, key, value]. Ops: "put", "del", and "snap" (value = packed
# {table: {key: value}} full state — a compaction checkpoint; replay resets
# to it and continues).
_HDR = struct.Struct("<II")


def _frame(op: str, table: str, key: str, value: Optional[bytes]) -> bytes:
    body = msgpack.packb([op, table, key, value], use_bin_type=True)
    return _HDR.pack(len(body), zlib.crc32(body)) + body


class WalStoreClient(StoreClient):
    """Append-only group-commit log (see module docstring).

    Reads are served from a full in-memory mirror; every mutation appends a
    frame to an in-process buffer and schedules one flush per event-loop
    tick (``loop.call_soon``), so N mutations in one handler burst cost one
    ``os.write`` + one fsync instead of N. Without a running loop (direct
    library use, tests) each mutation flushes inline.
    """

    def __init__(
        self,
        path: str,
        sync: Optional[str] = None,
        compact_bytes: Optional[int] = None,
    ):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._sync = sync or config.gcs_store_sync
        self._compact_bytes = (
            config.gcs_wal_compact_bytes if compact_bytes is None else compact_bytes
        )
        self._lock = threading.Lock()
        self._closed = False
        self._tables: Dict[str, Dict[str, bytes]] = {}
        self._pending: list = []
        self._flush_scheduled = False
        self._recover()
        self._fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        self._log_bytes = os.fstat(self._fd).st_size

    # -- recovery ------------------------------------------------------------

    def _recover(self) -> None:
        """Replay the log into the mirror; truncate at the first torn or
        corrupt record (a crash mid-append leaves a short header, a short
        body, or a body whose CRC does not match — everything before it is
        intact and everything after it was never acknowledged as flushed)."""
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            data = f.read()
        if data.startswith(b"SQLite format 3"):
            # Backend switched under an existing file: refuse rather than
            # "recover" a sqlite db into an empty log (torn-tail truncation
            # at offset 0 would destroy it).
            raise ValueError(
                f"{self._path} is a sqlite store; set gcs_persist_backend="
                "sqlite or remove the file"
            )
        off = 0
        good = 0
        while off + _HDR.size <= len(data):
            blen, crc = _HDR.unpack_from(data, off)
            body = data[off + _HDR.size : off + _HDR.size + blen]
            if len(body) < blen or zlib.crc32(body) != crc:
                break  # torn tail
            op, table, key, value = msgpack.unpackb(body, raw=False)
            if op == "snap":
                self._tables = {
                    t: dict(kv)
                    for t, kv in msgpack.unpackb(value, raw=False).items()
                }
            elif op == "put":
                self._tables.setdefault(table, {})[key] = value
            else:  # "del"
                self._tables.get(table, {}).pop(key, None)
            off += _HDR.size + blen
            good = off
        if good < len(data):
            with open(self._path, "r+b") as f:
                f.truncate(good)

    # -- group commit --------------------------------------------------------

    def _schedule_flush(self) -> None:
        if self._sync == "always":
            # Per-record durability: no group commit, fsync inline.
            self._flush()
            return
        if self._flush_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._flush()
            return
        self._flush_scheduled = True
        loop.call_soon(self.flush)

    def flush(self) -> None:
        """Write and (per sync policy) fsync all buffered frames: the group
        commit. Public so shutdown paths can force the tail out."""
        with self._lock:
            self._flush_scheduled = False
            self._flush()

    def _flush(self) -> None:  # caller holds _lock (or is single-threaded init)
        if not self._pending or self._closed:
            self._pending.clear()
            return
        buf = b"".join(self._pending)
        self._pending.clear()
        t0 = time.perf_counter()
        os.write(self._fd, buf)
        if self._sync != "off":
            os.fsync(self._fd)
        _TEL_WRITE_S.default.observe(time.perf_counter() - t0)
        _TEL_WAL_BYTES.default.inc(len(buf))
        self._log_bytes += len(buf)
        if self._compact_bytes and self._log_bytes > self._compact_bytes:
            self._compact()

    def _compact(self) -> None:
        """Snapshot compaction: write the full mirror as one "snap" frame to
        a temp file and atomically rename it over the log. Readers of the
        old file (none — the GCS is the only client) and a crash at any
        point see either the old log or the complete snapshot."""
        snap = _frame(
            "snap",
            "",
            "",
            msgpack.packb(self._tables, use_bin_type=True),
        )
        tmp = self._path + ".compact"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, snap)
            if self._sync != "off":
                os.fsync(fd)
        finally:
            os.close(fd)
        os.rename(tmp, self._path)
        os.close(self._fd)
        self._fd = os.open(self._path, os.O_WRONLY | os.O_APPEND)
        self._log_bytes = len(snap)
        _TEL_WAL_COMPACTIONS.default.inc()

    # -- StoreClient API -----------------------------------------------------

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return
            self._tables.setdefault(table, {})[key] = value
            self._pending.append(_frame("put", table, key, value))
            self._schedule_flush()

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._tables.get(table, {}).pop(key, None)
            self._pending.append(_frame("del", table, key, None))
            self._schedule_flush()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush()
            self._closed = True
            try:
                if self._sync != "off":
                    os.fsync(self._fd)
            except OSError:
                pass
            os.close(self._fd)

    def crash(self) -> None:
        """Kill -9 analog: the buffered tail reaches the OS (an in-process
        buffer is an artifact of the simulation — a real group-commit store
        writes before acking) but is NOT fsynced, and no compaction or
        checkpoint runs."""
        with self._lock:
            if self._closed:
                return
            buf = b"".join(self._pending)
            self._pending.clear()
            self._closed = True
            if buf:
                os.write(self._fd, buf)
                self._log_bytes += len(buf)
            os.close(self._fd)


def inject_torn_tail(path: str) -> bool:
    """Append a partial frame to a WAL file — the on-disk shape of a crash
    that died mid-append of a NEW record (its header landed, its body did
    not). Recovery must truncate it without losing any earlier record.
    Returns False (no-op) for non-WAL persistence files (sqlite)."""
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        head = f.read(16)
    if head[:16].startswith(b"SQLite format 3"):
        return False
    with open(path, "ab") as f:
        f.write(_HDR.pack(512, 0xDEADBEEF) + b"\x00" * 17)  # 512-byte body cut short
    return True


def make_store(
    persist_path: Optional[str], backend: Optional[str] = None
) -> StoreClient:
    """Build the configured store. No path -> in-memory regardless of
    backend; with a path, ``backend`` (default: the ``gcs_persist_backend``
    knob) picks wal / sqlite / memory."""
    if not persist_path:
        return InMemoryStoreClient()
    backend = backend or config.gcs_persist_backend
    if backend == "sqlite":
        return SqliteStoreClient(persist_path)
    if backend == "memory":
        return InMemoryStoreClient()
    if backend != "wal":
        raise ValueError(f"unknown gcs_persist_backend {backend!r}")
    return WalStoreClient(persist_path)
