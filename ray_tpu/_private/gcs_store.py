"""GCS persistence: pluggable store clients.

TPU-native analog of the reference's StoreClient abstraction
(src/ray/gcs/store_client/store_client.h:33) with the two shipped
implementations mirrored: in-memory (in_memory_store_client.h:31 — the
default; state dies with the GCS) and a durable backend for GCS fault
tolerance. The reference uses Redis (redis_store_client.h:33) because its
GCS is a separate process fleet; here a local sqlite file gives the same
property — the control plane's tables survive a GCS restart — without an
external service. Table layout follows the reference's gcs_table_storage.cc
(one logical table per domain: kv, actors, named, jobs, pgs).

All values are opaque bytes (the GCS msgpacks its own records).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Iterable, Optional, Tuple


class StoreClient:
    """Abstract synchronous KV-per-table store."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def get_all(self, table: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """Default: no durability (reference in_memory_store_client.h:31)."""

    def __init__(self):
        self._tables: Dict[str, Dict[str, bytes]] = {}

    def put(self, table: str, key: str, value: bytes) -> None:
        self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key: str) -> Optional[bytes]:
        return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: str) -> None:
        self._tables.get(table, {}).pop(key, None)

    def get_all(self, table: str) -> Dict[str, bytes]:
        return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable file-backed store for GCS fault tolerance.

    WAL mode + one flat table; writes are a few hundred bytes each and run
    inline on the GCS loop (sub-ms on local disk, same order as the
    reference's Redis round trip from the GCS process).
    """

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key TEXT, value BLOB,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table: str, key: str, value: bytes) -> None:
        with self._lock:
            if self._closed:
                return  # shutdown race: a trailing handler after stop()
            self._db.execute(
                "INSERT OR REPLACE INTO gcs (tbl, key, value) VALUES (?, ?, ?)",
                (table, key, value),
            )
            self._db.commit()

    def get(self, table: str, key: str) -> Optional[bytes]:
        with self._lock:
            if self._closed:
                return None
            row = self._db.execute(
                "SELECT value FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._db.execute(
                "DELETE FROM gcs WHERE tbl = ? AND key = ?", (table, key)
            )
            self._db.commit()

    def get_all(self, table: str) -> Dict[str, bytes]:
        with self._lock:
            if self._closed:
                return {}
            rows = self._db.execute(
                "SELECT key, value FROM gcs WHERE tbl = ?", (table,)
            ).fetchall()
        return {k: bytes(v) for k, v in rows}

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._db.close()


def make_store(persist_path: Optional[str]) -> StoreClient:
    if persist_path:
        return SqliteStoreClient(persist_path)
    return InMemoryStoreClient()
