"""Object stores seen from inside a worker/driver process.

Two tiers, mirroring the reference's CoreWorker store providers
(src/ray/core_worker/store_provider/):

- MemoryStore: owner-local in-process store for small objects and for
  "where is it" markers of large objects that live in shm. Futures/waiters
  let `get` block until a pending task fills the slot.
- PlasmaClient: client of the local raylet's object directory; data moves
  through named shm segments (zero-copy reads via memoryview).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import rpc, shm
from ray_tpu._private.common import ObjectLostError, config

logger = logging.getLogger(__name__)

# Memory-store entry kinds.
INLINE = "inline"  # payload bytes present locally
IN_PLASMA = "plasma"  # payload in shm on some node (addr attached)


class MemoryStoreEntry:
    __slots__ = ("kind", "payload", "plasma_addr")

    def __init__(self, kind: str, payload: Optional[bytes], plasma_addr=None):
        self.kind = kind
        self.payload = payload
        self.plasma_addr = plasma_addr  # raylet addr holding the primary copy


class MemoryStore:
    def __init__(self):
        self._entries: Dict[str, MemoryStoreEntry] = {}
        self._waiters: Dict[str, List[asyncio.Future]] = {}

    def contains(self, oid: str) -> bool:
        return oid in self._entries

    def get(self, oid: str) -> Optional[MemoryStoreEntry]:
        return self._entries.get(oid)

    def put_inline(self, oid: str, payload: bytes) -> None:
        self._entries[oid] = MemoryStoreEntry(INLINE, payload)
        self._notify(oid)

    def put_plasma_marker(self, oid: str, plasma_addr: Tuple[str, int]) -> None:
        self._entries[oid] = MemoryStoreEntry(IN_PLASMA, None, tuple(plasma_addr))
        self._notify(oid)

    def delete(self, oid: str) -> None:
        self._entries.pop(oid, None)

    def _notify(self, oid: str) -> None:
        for fut in self._waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    async def wait_for(self, oid: str, timeout: Optional[float]) -> Optional[MemoryStoreEntry]:
        entry = self._entries.get(oid)
        if entry is not None:
            return entry
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(oid, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        return self._entries.get(oid)


class PlasmaClient:
    """Client of the local raylet's shm object directory.

    Mapped segments are held (pinned client-side) until `release`; reads are
    zero-copy memoryviews into the segment.
    """

    def __init__(self, raylet_conn: rpc.Connection):
        self.conn = raylet_conn
        self._segments: Dict[str, shm.Segment] = {}
        self._deferred_close: List[shm.Segment] = []

    async def put_serialized(self, oid: str, serialized) -> None:
        size = max(1, serialized.total_size)
        reply = await self.conn.call("ObjCreate", {"oid": oid, "size": size, "pin": True})
        if reply.get("exists"):
            return  # already stored (e.g. deterministic re-execution)
        seg = shm.create(reply["name"], size)
        try:
            serialized.write_to(seg.view)
        finally:
            seg.close()
        await self.conn.call("ObjSeal", {"oid": oid})

    async def put_bytes(self, oid: str, payload: bytes) -> None:
        reply = await self.conn.call(
            "ObjCreate", {"oid": oid, "size": max(1, len(payload)), "pin": True}
        )
        if reply.get("exists"):
            return
        seg = shm.create(reply["name"], max(1, len(payload)))
        try:
            seg.view[: len(payload)] = payload
        finally:
            seg.close()
        await self.conn.call("ObjSeal", {"oid": oid})

    async def get(
        self, oids: List[str], timeout: Optional[float] = None, block: bool = True
    ) -> Tuple[Dict[str, memoryview], List[str]]:
        reply = await self.conn.call(
            "ObjGet",
            {"oids": oids, "timeout": timeout, "block": block},
            timeout=None if timeout is None else timeout + 10,
        )
        found: Dict[str, memoryview] = {}
        for oid, meta in reply["found"].items():
            seg = self._segments.get(oid)
            if seg is None:
                seg = shm.open_ro(meta["name"])
                self._segments[oid] = seg
            found[oid] = seg.view
        return found, reply["missing"]

    async def contains(self, oids: List[str]) -> Dict[str, bool]:
        reply = await self.conn.call("ObjContains", {"oids": oids})
        return reply["contains"]

    async def pull(self, oid: str, from_addr: Tuple[str, int]) -> memoryview:
        """Ask the local raylet to fetch a remote object, then map it."""
        await self.conn.call(
            "PullObject", {"oid": oid, "from_addr": list(from_addr)}, timeout=300
        )
        found, missing = await self.get([oid], timeout=30)
        if oid in found:
            return found[oid]
        raise ObjectLostError(f"pull of {oid[:12]} failed: {missing}")

    def release(self, oid: str) -> None:
        seg = self._segments.pop(oid, None)
        if seg is not None:
            self._close_or_defer(seg)
        # Opportunistically retry deferred closes.
        still = []
        for s in self._deferred_close:
            try:
                s.close()
            except Exception:
                still.append(s)
        self._deferred_close = still

    def _close_or_defer(self, seg: shm.Segment) -> None:
        try:
            seg.close()
        except Exception:
            # memoryviews into the segment are still alive; retry later.
            self._deferred_close.append(seg)

    async def delete(self, oids: List[str]) -> None:
        for oid in oids:
            self.release(oid)
        await self.conn.call("ObjDelete", {"oids": oids})

    def close(self) -> None:
        for oid in list(self._segments):
            self.release(oid)
