"""Object stores seen from inside a worker/driver process.

Two tiers, mirroring the reference's CoreWorker store providers
(src/ray/core_worker/store_provider/):

- MemoryStore: owner-local in-process store for small objects and for
  "where is it" markers of large objects that live in shm. Futures/waiters
  let `get` block until a pending task fills the slot.
- PlasmaClient: client of the local raylet's object directory; data moves
  through named shm segments (zero-copy reads via memoryview).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import rpc, shm, telemetry
from ray_tpu._private.common import ObjectLostError, config

logger = logging.getLogger(__name__)

_TEL_PUT_BYTES = telemetry.counter(
    "object", "put_bytes", "bytes written into the local shm arena"
)
_TEL_GET_BYTES = telemetry.counter(
    "object", "get_bytes", "bytes mapped from the local shm arena by get()"
)
_TEL_PUT_LAT = telemetry.histogram(
    "object", "put_latency_s", "plasma put (create+write+seal) latency",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_GET_LAT = telemetry.histogram(
    "object", "get_latency_s", "plasma get round-trip latency",
    buckets=telemetry.LATENCY_BUCKETS_S,
)
_TEL_PULLS = telemetry.counter(
    "object", "pulls", "remote-object pulls requested via the local raylet"
)
_TEL_RELEASE_FLUSHES = telemetry.counter(
    "object", "release_flushes", "debounced batched-release flushes"
)
_TEL_RELEASE_OIDS = telemetry.counter(
    "object", "release_oids", "object holds dropped via batched release"
)

# Memory-store entry kinds.
INLINE = "inline"  # payload bytes present locally
IN_PLASMA = "plasma"  # payload in shm on some node (addr attached)


def _span(name: str, start: float, duration: float, **attrs) -> None:
    """Record an object-plane span into the active trace. Callers guard on
    ``rpc._trace_ctx`` being set, so the lazy import (which cycles through
    ray_tpu.util at module scope) only runs when a trace is live."""
    from ray_tpu.util import tracing

    tracing.record_span(name, "object", start, duration, **attrs)


class MemoryStoreEntry:
    __slots__ = ("kind", "payload", "plasma_addr")

    def __init__(self, kind: str, payload: Optional[bytes], plasma_addr=None):
        self.kind = kind
        self.payload = payload
        self.plasma_addr = plasma_addr  # raylet addr holding the primary copy


class MemoryStore:
    def __init__(self):
        self._entries: Dict[str, MemoryStoreEntry] = {}
        self._waiters: Dict[str, List[asyncio.Future]] = {}

    def contains(self, oid: str) -> bool:
        return oid in self._entries

    def get(self, oid: str) -> Optional[MemoryStoreEntry]:
        return self._entries.get(oid)

    def put_inline(self, oid: str, payload: bytes) -> None:
        self._entries[oid] = MemoryStoreEntry(INLINE, payload)
        self._notify(oid)

    def put_plasma_marker(self, oid: str, plasma_addr: Tuple[str, int]) -> None:
        self._entries[oid] = MemoryStoreEntry(IN_PLASMA, None, tuple(plasma_addr))
        self._notify(oid)

    def delete(self, oid: str) -> None:
        self._entries.pop(oid, None)

    def plasma_oids_at(self, addr) -> List[str]:
        """Objects whose primary copy lives in the arena at ``addr`` — the
        set a node death at that address makes candidates for lineage
        reconstruction."""
        addr = tuple(addr)
        return [
            oid
            for oid, e in self._entries.items()
            if e.kind == IN_PLASMA and e.plasma_addr == addr
        ]

    def _notify(self, oid: str) -> None:
        for fut in self._waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    async def wait_for(self, oid: str, timeout: Optional[float]) -> Optional[MemoryStoreEntry]:
        entry = self._entries.get(oid)
        if entry is not None:
            return entry
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(oid, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        return self._entries.get(oid)


class PlasmaClient:
    """Client of the local raylet's shm arena store.

    The node's whole object store is one shm arena; the client maps it once
    (read-write — puts write directly at raylet-assigned offsets) and every
    read is a zero-copy memoryview slice at an offset. Mirrors the reference
    plasma client's single-mmap attach (plasma/client.h).
    """

    def __init__(self, raylet_conn: rpc.Connection):
        self.conn = raylet_conn
        self._arenas: Dict[str, shm.Segment] = {}
        # Objects this client holds (the raylet counts a hold per ObjGet and
        # will not recycle their bytes until released / disconnect).
        self.held: Dict[str, int] = {}
        # Debounced release batch: oids queued by release() in one loop tick
        # flush as a single ObjRelease call (value drops arrive in bursts
        # when a task's deserialized arguments are collected together).
        self._release_pending: set = set()
        self._release_flush_scheduled = False
        # Last in-flight batched-release task (tests/benchmarks await it to
        # observe flush completion; the path itself is fire-and-forget).
        self._release_task: Optional[asyncio.Task] = None

    def _arena_view(self, name: str) -> memoryview:
        seg = self._arenas.get(name)
        if seg is None:
            seg = shm.open_rw(name)
            self._arenas[name] = seg
        return seg.view

    def _slice(self, meta: dict) -> memoryview:
        view = self._arena_view(meta["arena"])
        off, size = meta["offset"], meta["size"]
        return view[off : off + size]

    async def put_serialized(self, oid: str, serialized) -> None:
        t0 = time.monotonic()
        ws = time.time()
        size = max(1, serialized.total_size)
        reply = await self.conn.call("ObjCreate", {"oid": oid, "size": size, "pin": True})
        if reply.get("exists"):
            return  # already stored (e.g. deterministic re-execution)
        serialized.write_to(self._slice(reply))
        _TEL_PUT_BYTES.inc(size)
        _TEL_PUT_LAT.observe(time.monotonic() - t0)
        if rpc._trace_ctx.get() is not None:
            _span("object.put", ws, time.monotonic() - t0, oid=oid, size=size)
        # Seal as a one-way push: same-connection FIFO means our own later
        # ObjGet/ObjCreate calls observe the seal, and remote readers reach
        # the raylet after the owner advertises the object — both ordered
        # after this frame. Saves the second RTT of every large put.
        self.conn.push_nowait("ObjSeal", {"oid": oid})

    async def put_bytes(self, oid: str, payload: bytes) -> None:
        t0 = time.monotonic()
        reply = await self.conn.call(
            "ObjCreate", {"oid": oid, "size": max(1, len(payload)), "pin": True}
        )
        if reply.get("exists"):
            return
        shm.copy_into(self._slice(reply), payload)
        self.conn.push_nowait("ObjSeal", {"oid": oid})
        _TEL_PUT_BYTES.inc(max(1, len(payload)))
        _TEL_PUT_LAT.observe(time.monotonic() - t0)

    async def get(
        self, oids: List[str], timeout: Optional[float] = None, block: bool = True
    ) -> Tuple[Dict[str, memoryview], List[str]]:
        t0 = time.monotonic()
        reply = await self.conn.call(
            "ObjGet",
            {"oids": oids, "timeout": timeout, "block": block},
            timeout=None if timeout is None else timeout + 10,
        )
        found: Dict[str, memoryview] = {}
        for oid, meta in reply["found"].items():
            self.held[oid] = self.held.get(oid, 0) + 1
            found[oid] = self._slice(meta)
            _TEL_GET_BYTES.inc(meta["size"])
        _TEL_GET_LAT.observe(time.monotonic() - t0)
        if rpc._trace_ctx.get() is not None:
            _span(
                "object.get",
                time.time() - (time.monotonic() - t0),
                time.monotonic() - t0,
                count=len(oids),
            )
        return found, reply["missing"]

    async def contains(self, oids: List[str]) -> Dict[str, bool]:
        reply = await self.conn.call("ObjContains", {"oids": oids})
        return reply["contains"]

    async def pull(
        self, oid: str, from_addr: Tuple[str, int], purpose: str = "get"
    ) -> memoryview:
        """Ask the local raylet to fetch a remote object, then map it.
        purpose feeds the raylet's prioritized pull admission (reference:
        pull_manager.h): "get" > "wait" > "task_arg"."""
        _TEL_PULLS.inc()
        t0 = time.monotonic()
        ws = time.time()
        meta = await self.conn.call(
            "PullObject",
            {"oid": oid, "from_addr": list(from_addr), "purpose": purpose},
            timeout=config.rpc_pull_timeout_s,
        )
        if rpc._trace_ctx.get() is not None:
            _span(
                "object.pull", ws, time.monotonic() - t0, oid=oid, purpose=purpose
            )
        if meta.get("offset") is not None:
            self.held[oid] = self.held.get(oid, 0) + 1
            return self._slice(meta)
        found, missing = await self.get(
            [oid], timeout=config.rpc_object_get_timeout_s
        )
        if oid in found:
            return found[oid]
        raise ObjectLostError(f"pull of {oid[:12]} failed: {missing}")

    async def release_many(self, oids: List[str]) -> None:
        """Drop ALL of this client's holds on the given objects."""
        await self.release_counts({oid: self.held.get(oid, 0) for oid in oids})

    def release(self, oid: str) -> None:
        """Fire-and-forget release (LRU touch + all-holds drop). Coalesced:
        every release() in the same loop tick joins one debounced batch that
        flushes as a single ObjRelease call — N value drops used to cost N
        spawned tasks and N RPCs."""
        self._release_pending.add(oid)
        if self._release_flush_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no running loop (sync teardown path)
            self._release_pending.discard(oid)
            return
        self._release_flush_scheduled = True
        loop.call_soon(self._flush_releases)

    def _flush_releases(self) -> None:
        self._release_flush_scheduled = False
        pending, self._release_pending = self._release_pending, set()
        if not pending or self.conn.closed:
            return
        _TEL_RELEASE_FLUSHES.inc()
        _TEL_RELEASE_OIDS.inc(len(pending))
        task = rpc.spawn(self.release_many(list(pending)))
        # Retrieve any exception so a closed connection doesn't log noise.
        task.add_done_callback(
            lambda t: t.exception() if not t.cancelled() else None
        )
        self._release_task = task

    async def release_counts(self, counts: Dict[str, int]) -> None:
        """Drop up to ``counts[oid]`` holds per object (value-lifetime holds:
        each deserialized value carries one hold, released when the value is
        garbage collected — reference: plasma client buffer refcounts)."""
        to_send = []
        for oid, n in counts.items():
            have = self.held.get(oid, 0)
            take = min(have, n)
            if take <= 0:
                continue
            if have - take <= 0:
                self.held.pop(oid, None)
            else:
                self.held[oid] = have - take
            to_send.extend([oid] * take)
        if not to_send:
            return
        try:
            await self.conn.call("ObjRelease", {"oids": to_send})
        except rpc.RpcError:
            pass

    async def delete(self, oids: List[str]) -> None:
        await self.conn.call("ObjDelete", {"oids": oids})

    async def spill(self, oids: List[str]) -> Dict[str, List[str]]:
        """Direct the raylet to spill the given sealed objects to external
        storage now (owner-driven eviction; ray._private.internal_api
        force-spill analog). Returns {"spilled": [...], "rejected": [...]} —
        held/unsealed/pinned objects are rejected, not errors."""
        return await self.conn.call(
            "SpillObjects", {"oids": oids},
            timeout=config.rpc_transfer_timeout_s,
        )

    async def restore(self, oid: str) -> bool:
        """Ask the raylet to restore one spilled object into the arena."""
        reply = await self.conn.call(
            "RestoreSpilled", {"oid": oid},
            timeout=config.rpc_transfer_timeout_s,
        )
        return bool(reply.get("restored"))

    async def pin(self, oid: str, pin: bool = True) -> bool:
        """Pin (or unpin) an object against spilling/eviction."""
        reply = await self.conn.call(
            "PinObject", {"oid": oid, "pin": pin},
            timeout=config.rpc_control_timeout_s,
        )
        return bool(reply.get("ok"))

    def close(self) -> None:
        for seg in self._arenas.values():
            try:
                seg.close()
            except Exception:
                pass  # live views into the arena keep the mapping alive
        self._arenas = {}
