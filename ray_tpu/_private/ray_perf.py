"""Control-plane microbenchmarks (port of the reference's
python/ray/_private/ray_perf.py:93-288 suite set).

Run: python -m ray_tpu._private.ray_perf [--json PATH]

Suites: trivial task throughput (sync + pipelined), actor call throughput
(1:1 sync, 1:1 async batch, n:n), put/get small objects. Each prints a
line; with --json, a summary dict is written for the driver/CI.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict

import ray_tpu


def timeit(name: str, fn, multiplier: int = 1) -> float:
    # Warmup, then 3 timed trials (reference ray_perf style).
    fn()
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        rate = multiplier / dt
        best = max(best, rate)
    print(f"{name}: {best:.1f} /s")
    return best


def main(json_path: str = "") -> Dict[str, float]:
    results: Dict[str, float] = {}
    ray_tpu.init(num_cpus=8, num_tpus=0)

    @ray_tpu.remote
    def trivial():
        return b"ok"

    # Separate sync and async actor classes (reference ray_perf.py does the
    # same): an actor with any coroutine method is an asyncio actor, whose
    # calls all run on the event loop rather than the dedicated exec thread.
    @ray_tpu.remote
    class Counter:
        def small(self):
            return b"ok"

    @ray_tpu.remote
    class AsyncCounter:
        async def asmall(self):
            return b"ok"

    # Warm the worker pool so spawn cost is not measured.
    ray_tpu.get([trivial.remote() for _ in range(16)])

    N = 1000
    results["tasks_sync_per_s"] = timeit(
        "single client tasks sync",
        lambda: [ray_tpu.get(trivial.remote()) for _ in range(100)],
        100,
    )
    results["tasks_async_per_s"] = timeit(
        "single client tasks async (pipelined)",
        lambda: ray_tpu.get([trivial.remote() for _ in range(N)]),
        N,
    )

    actor = Counter.remote()
    ray_tpu.get(actor.small.remote())
    results["actor_calls_sync_per_s"] = timeit(
        "1:1 actor calls sync",
        lambda: [ray_tpu.get(actor.small.remote()) for _ in range(100)],
        100,
    )
    results["actor_calls_async_per_s"] = timeit(
        "1:1 actor calls async (pipelined)",
        lambda: ray_tpu.get([actor.small.remote() for _ in range(N)]),
        N,
    )

    ray_tpu.kill(actor)
    async_actor = AsyncCounter.options(max_concurrency=64).remote()
    ray_tpu.get(async_actor.asmall.remote())
    results["async_actor_calls_per_s"] = timeit(
        "1:1 async actor calls (pipelined)",
        lambda: ray_tpu.get([async_actor.asmall.remote() for _ in range(N)]),
        N,
    )

    ray_tpu.kill(async_actor)
    n_actors = 4
    actors = [Counter.remote() for _ in range(n_actors)]
    ray_tpu.get([a.small.remote() for a in actors])
    results["nn_actor_calls_per_s"] = timeit(
        "n:n actor calls (4 actors, pipelined)",
        lambda: ray_tpu.get(
            [a.small.remote() for _ in range(N // n_actors) for a in actors]
        ),
        N,
    )

    for a in actors:
        ray_tpu.kill(a)
    small = b"x" * 1024
    results["put_small_per_s"] = timeit(
        "1KB put", lambda: [ray_tpu.put(small) for _ in range(500)], 500
    )
    ref = ray_tpu.put(small)
    results["get_small_per_s"] = timeit(
        "1KB get", lambda: [ray_tpu.get(ref) for _ in range(500)], 500
    )

    import numpy as np

    big = np.zeros(16 * 1024 * 1024 // 8)  # 16 MB
    results["put_16mb_per_s"] = timeit(
        "16MB put (shm)", lambda: [ray_tpu.put(big) for _ in range(20)], 20
    )
    bref = ray_tpu.put(big)
    results["get_16mb_per_s"] = timeit(
        "16MB get (zero-copy)", lambda: [ray_tpu.get(bref) for _ in range(50)], 50
    )

    ray_tpu.shutdown()
    if json_path:
        with open(json_path, "w") as f:
            json.dump(results, f, indent=2)
    return results


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--json", default="")
    args = parser.parse_args()
    main(args.json)
