"""Control-plane microbenchmarks (port of the reference's
python/ray/_private/ray_perf.py:93-288 suite set).

Run: python -m ray_tpu._private.ray_perf [--json PATH]

Suites: trivial task throughput (sync + pipelined), actor call throughput
(1:1 sync, 1:1 async batch, n:n), put/get small objects. Each prints a
line; with --json, a summary dict is written for the driver/CI.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, Optional

import ray_tpu


def timeit(name: str, fn, multiplier: int = 1) -> float:
    # Warmup, then 3 timed trials (reference ray_perf style).
    fn()
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        rate = multiplier / dt
        best = max(best, rate)
    print(f"{name}: {best:.1f} /s")
    return best


def _bench_release_batched() -> float:
    """Rate of plasma hold drops through the debounced release() batch:
    one cycle takes holds on N objects (one ObjGet), queues N release()
    calls, and awaits the single coalesced ObjRelease flush."""
    import numpy as np

    from ray_tpu._private import worker as worker_mod

    n = 200
    payload = np.zeros(256 * 1024, dtype=np.uint8)  # plasma-sized
    refs = [ray_tpu.put(payload) for _ in range(n)]
    oids = [r.hex() for r in refs]
    w = worker_mod.global_worker
    plasma = w.core.plasma

    async def _cycle():
        found, _ = await plasma.get(oids)
        del found
        for oid in oids:
            plasma.release(oid)
        await asyncio.sleep(0)  # run the call_soon flush
        task = plasma._release_task
        if task is not None:
            await task

    rate = timeit(
        "batched release (200 holds)", lambda: w.run_async(_cycle(), 60), n
    )
    del refs
    return rate


def _bench_sched() -> Dict[str, float]:
    """Scheduler throughput on the simulated cluster: N raylets (real lease
    scheduler, loopback RPC, in-process stub workers — sim_cluster.py) with
    10k 1-CPU lease/release cycles driven through the core_worker spillback
    protocol at bounded concurrency. Runs after shutdown(): the sim owns
    its own loop and config env."""
    import os

    from ray_tpu._private.sim_cluster import SimCluster, SimLeaseClient

    nodes = int(os.environ.get("RAY_TPU_SCHED_BENCH_NODES", "500"))
    tasks = int(os.environ.get("RAY_TPU_SCHED_BENCH_TASKS", "10000"))
    concurrency = int(os.environ.get("RAY_TPU_SCHED_BENCH_CONCURRENCY", "64"))
    cluster = SimCluster(nodes).start()
    client = SimLeaseClient(cluster)

    async def schedule_all(n: int) -> None:
        sem = asyncio.Semaphore(concurrency)
        entries = [tuple(r.addr) for r in cluster.raylets.values()]

        async def one(i: int) -> None:
            async with sem:
                await client.lease_cycle(
                    {"CPU": 1.0}, entry_addr=entries[i % len(entries)]
                )

        await asyncio.gather(*(one(i) for i in range(n)))

    try:
        cluster.run(schedule_all(min(tasks, 500)), timeout=120)  # warmup
        t0 = time.perf_counter()
        cluster.run(schedule_all(tasks), timeout=600)
        dt = time.perf_counter() - t0
    finally:
        cluster.run(client.close(), timeout=30)
        cluster.shutdown()
    rate = tasks / dt
    wall_10k = dt * (10_000 / tasks)
    print(f"sched leases ({nodes} sim nodes): {rate:.1f} /s")
    print(f"time to schedule 10k tasks: {wall_10k:.2f} s")
    return {
        "leases_per_s": rate,
        "time_to_schedule_10k_tasks_s": wall_10k,
    }


def _bench_gcs_persist(
    replicated: bool = False, followers: Optional[int] = None
) -> float:
    """Write-through rate of the persistent store under group commit: each
    cycle issues N keyed puts inside one event-loop context and then runs
    the per-tick flush — one os.write + one fsync for the whole batch, the
    shape every GCS control-plane mutation pays (docs/fault_tolerance.md
    "Durability contract"). With ``replicated=True`` the same workload runs
    through ReplicatedStoreClient; ``followers=1`` pins the historical
    wait-for-all 2-member shape (every flush fsyncs primary AND the single
    follower before ack), while the default 2-follower group acks at the
    majority (2 of 3) with the laggard catching up off the commit path —
    the HA deployment's quorum write path."""
    import os
    import shutil
    import tempfile

    from ray_tpu._private.gcs_store import (
        ReplicatedStoreClient,
        WalStoreClient,
        follower_paths,
    )

    d = tempfile.mkdtemp(prefix="perf_wal_")
    if replicated:
        path = os.path.join(d, "gcs.wal")
        fols = follower_paths(path, followers) if followers else None
        store = ReplicatedStoreClient(path, followers=fols, term=1)
        label = (
            f"gcs persist puts (replicated, {followers} follower)"
            if followers
            else f"gcs persist puts (quorum {store.quorum} of "
            f"{len(store._members)})"
        )
    else:
        store = WalStoreClient(os.path.join(d, "gcs.wal"))
        label = "gcs persist puts (wal group commit)"
    n = 2000
    payload = b"v" * 256
    seq = [0]

    def cycle():
        base = seq[0]
        seq[0] += n

        async def burst():
            # Keyed overwrites: the table stays bounded, the log grows and
            # periodically compacts — the steady-state GCS write pattern.
            for i in range(n):
                store.put("kv", f"k{(base + i) % 512}", payload)
            store.flush()

        asyncio.run(burst())

    try:
        rate = timeit(label, cycle, n)
    finally:
        store.close()
        shutil.rmtree(d, ignore_errors=True)
    return rate


def _bench_gcs_failover() -> float:
    """Time to a converged control-plane view after whole-machine GCS loss:
    a SimCluster in HA mode (replicated store + warm standby) loses the
    primary GCS process AND its log member; the clock runs from the kill
    until the promoted leader's node view reports every raylet ALIVE again
    (promotion + leader-file flip + the full reconnect/re-report wave)."""
    import os
    import shutil
    import tempfile

    from ray_tpu._private import rpc
    from ray_tpu._private.common import config
    from ray_tpu._private.sim_cluster import SimCluster

    nodes = int(os.environ.get("RAY_TPU_FAILOVER_BENCH_NODES", "100"))
    d = tempfile.mkdtemp(prefix="perf_failover_")
    cluster = SimCluster(
        nodes,
        persist_path=os.path.join(d, "gcs.wal"),
        ha=True,
        env={
            "RAY_TPU_GCS_LEADER_LEASE_S": "1.0",
            "RAY_TPU_GCS_STANDBY_POLL_S": "0.05",
        },
    ).start()
    try:
        t0 = time.perf_counter()
        assert cluster.run(cluster.kill_gcs_host_async(), timeout=120)

        async def converged() -> None:
            conn = await rpc.connect(*cluster.gcs_addr)
            try:
                while True:
                    reply = await conn.call(
                        "GetAllNodes", timeout=config.rpc_reconnect_timeout_s
                    )
                    alive = sum(
                        1 for nd in reply["nodes"] if nd["state"] == "ALIVE"
                    )
                    if alive >= nodes:
                        return
                    await asyncio.sleep(0.1)
            finally:
                await conn.close()

        cluster.run(converged(), timeout=300)
        dt = time.perf_counter() - t0
    finally:
        cluster.shutdown()
        shutil.rmtree(d, ignore_errors=True)
    print(f"gcs failover -> converged view ({nodes} sim nodes): {dt:.2f} s")
    return dt


def _bench_pubsub_fanout() -> float:
    """Publisher fan-out with 1000 subscribers on one channel: each cycle
    publishes a burst in one loop tick and waits until every subscriber's
    drain task has pushed its PubBatch frames (packed once per chunk,
    written to every transport). Measures deliveries (message x
    subscriber) per second through the publisher machinery; transports are
    no-op sinks so the number isolates the control-plane fan-out cost a
    registration wave pays."""
    from ray_tpu._private.pubsub import Publisher

    n_subs = 1000
    burst = 32

    class _Sink:
        closed = False
        peername = "bench"

        def push_packed_nowait(self, data):
            pass

        def push_nowait(self, kind, payload):
            pass

        async def drain(self):
            pass

    pub = Publisher()
    for _ in range(n_subs):
        pub.subscribe("bench", _Sink())

    def cycle():
        async def one_tick():
            for i in range(burst):
                pub.publish("bench", {"i": i})
            await asyncio.sleep(0)  # run the scheduled flush
            while any(
                s.queued_msgs
                for subs in pub.channels.values()
                for s in subs.values()
            ):
                await asyncio.sleep(0)

        asyncio.run(one_tick())

    rate = timeit(
        "pubsub fan-out (1000 subscribers)", cycle, burst * n_subs
    )
    assert pub.total_dropped == 0, pub.total_dropped
    return rate


def _bench_telemetry_overhead() -> float:
    """Nanoseconds per hot-path telemetry record (one bound counter inc +
    one histogram observe) — the price every instrumented site pays. Gated
    with a ceiling: a regression here (a lock on the record path, an
    allocation per event) taxes every RPC frame and object operation."""
    from ray_tpu._private import telemetry

    c = telemetry.counter("perf", "overhead_probe", "overhead bench").default
    h = telemetry.histogram(
        "perf", "overhead_probe_s", "overhead bench",
        buckets=telemetry.LATENCY_BUCKETS_S,
    ).default
    n = 200_000
    for _ in range(10_000):  # warmup
        c.inc()
        h.observe(0.001)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
            h.observe(0.001)
        dt = time.perf_counter() - t0
        best = min(best, dt / n * 1e9)
    print(f"telemetry record overhead: {best:.0f} ns")
    return best


def _bench_trace_span_record() -> float:
    """Nanoseconds per runtime span record with tracing enabled and an
    active trace context — the price every instrumented hop (lease, arg
    fetch, object get/put, serve admission) pays on a sampled request.
    Gated with a ceiling: a regression here (id generation doing syscalls,
    lock contention on the buffer) taxes every traced hop. The disabled
    path is covered implicitly by the existing floors: with tracing off,
    instrumented sites reduce to one ContextVar.get() returning None."""
    from ray_tpu._private import rpc
    from ray_tpu.util import tracing

    prev = tracing.config.trace_sample_rate
    tracing.config.trace_sample_rate = 1.0
    tok = rpc._trace_ctx.set(("deadbeefdeadbeef", "cafebabecafebabe"))
    try:
        n = 200_000
        for _ in range(10_000):  # warmup
            tracing.record_span("perf.probe", "perf", 0.0, 0.001, oid="x")
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                tracing.record_span("perf.probe", "perf", 0.0, 0.001, oid="x")
            dt = time.perf_counter() - t0
            best = min(best, dt / n * 1e9)
    finally:
        rpc._trace_ctx.reset(tok)
        tracing.config.trace_sample_rate = prev
        tracing.reset()
    print(f"trace span record overhead: {best:.0f} ns")
    return best


def _bench_ingest() -> float:
    """Rows/s through the streaming ingest fast path: a fused read->map
    stage per block (metadata rides the refs), pipelined block fetch, and
    the zero-copy cursor batcher — i.e. execute -> iter_batches end to end
    on the driver (docs/perf.md "Ingest pipeline")."""
    import numpy as np

    import ray_tpu.data as rd

    n_blocks, rows_per_block, batch = 16, 4096, 256
    total = n_blocks * rows_per_block

    def synth(b):
        b["x"] = b["id"].astype(np.float64) * 2.0
        return b

    ds = rd.range(total, parallelism=n_blocks).map_batches(synth)

    def cycle():
        seen = 0
        for out in ds.iter_batches(
            batch_size=batch, batch_format="numpy", prefetch_batches=2
        ):
            seen += len(out["x"])
        assert seen == total, seen

    return timeit("ingest rows (execute->iter_batches)", cycle, total)


def _bench_transfer_16mb() -> float:
    """Two-node 16MB object transfers (PushChunk blob sidecar): each cycle
    produces fresh objects on node A and consumes them on node B, so every
    get crosses the wire."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    store = 512 * 1024 * 1024
    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    cluster.add_node(num_cpus=2, object_store_memory=store)
    cluster.add_node(num_cpus=2, object_store_memory=store)
    cluster.connect()
    try:

        @ray_tpu.remote(num_cpus=2)
        def produce(i):
            return np.full(16 * 1024 * 1024 // 8, float(i))

        @ray_tpu.remote(num_cpus=2)
        def consume(x):
            return float(x[0])

        nodes = [
            n for n in ray_tpu.nodes() if n["total"].get("CPU", 0) >= 20000
        ]
        n1, n2 = nodes[0]["node_id"], nodes[1]["node_id"]
        k = 3
        seq = [0]

        def cycle():
            base = seq[0]
            seq[0] += k
            refs = [
                produce.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(n1)
                ).remote(base + i)
                for i in range(k)
            ]
            outs = [
                consume.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(n2)
                ).remote(r)
                for r in refs
            ]
            ray_tpu.get(outs, timeout=120)

        return timeit("16MB cross-node transfer", cycle, k)
    finally:
        cluster.shutdown()


def _bench_spill() -> Dict[str, float]:
    """Object plane under memory pressure (docs/perf.md "Spilling"): a
    working set 4x the arena pushed through put + get, so the pressure loop
    spills the cold tail on the way in and the gets pay restores on the way
    out; then the same oversubscription driven through the data pipeline
    (execute -> iter_batches), counted in rows/s. Runs after shutdown():
    both phases boot their own small-arena session with filesystem
    spilling."""
    import os
    import shutil
    import tempfile

    import numpy as np

    spill_dir = tempfile.mkdtemp(prefix="ray_tpu_perf_spill_")
    saved = os.environ.get("RAY_TPU_OBJECT_SPILLING_CONFIG")
    os.environ["RAY_TPU_OBJECT_SPILLING_CONFIG"] = json.dumps(
        {"type": "filesystem", "params": {"directory_path": spill_dir}}
    )
    arena = 64 * 1024 * 1024
    obj = 8 * 1024 * 1024
    n = 4 * arena // obj  # 32 objects: working set 4x the arena
    results: Dict[str, float] = {}
    try:
        ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=arena)

        def cycle():
            refs = [
                ray_tpu.put(np.full(obj, i % 251, dtype=np.uint8))
                for i in range(n)
            ]
            for i, ref in enumerate(refs):
                out = ray_tpu.get(ref, timeout=120)
                assert out[0] == i % 251
                del out  # drop the zero-copy hold so the copy stays evictable

        mb = 2 * n * obj // (1024 * 1024)  # bytes spilled in + restored out
        results["spill_restore_mb_per_s"] = timeit(
            f"spill+restore round trip ({n * obj >> 20}MB through "
            f"{arena >> 20}MB arena)",
            cycle,
            mb,
        )
        ray_tpu.shutdown()

        # Same oversubscription end to end through the data pipeline: blocks
        # totaling 4x the arena must stream execute -> iter_batches with
        # zero errors while cold blocks spill and restore under the hood.
        ray_tpu.init(num_cpus=2, num_tpus=0, object_store_memory=arena)
        import ray_tpu.data as rd

        n_blocks = 16
        rows_per_block = (4 * arena) // n_blocks // 1024  # 1 KB rows
        total = n_blocks * rows_per_block

        def widen(b):
            out = dict(b)
            out["payload"] = np.zeros((len(b["id"]), 1024), dtype=np.uint8)
            return out

        ds = rd.range(total, parallelism=n_blocks).map_batches(widen)

        def data_cycle():
            seen = 0
            for out in ds.iter_batches(
                batch_size=4096, batch_format="numpy", prefetch_batches=2
            ):
                seen += len(out["payload"])
            assert seen == total, seen

        results["oversubscribed_put_rows_per_s"] = timeit(
            "oversubscribed ingest rows (4x arena, execute->iter_batches)",
            data_cycle,
            total,
        )
        ray_tpu.shutdown()
    finally:
        if saved is None:
            os.environ.pop("RAY_TPU_OBJECT_SPILLING_CONFIG", None)
        else:
            os.environ["RAY_TPU_OBJECT_SPILLING_CONFIG"] = saved
        shutil.rmtree(spill_dir, ignore_errors=True)
    return results


def _collective_child_main() -> None:
    """Child-process body for the collective allreduce bench.

    Runs in a fresh interpreter because jax must see the forced 8-device
    CPU mesh before its backend initializes, and the parent ray_perf
    process has already touched jax-adjacent state. Prints one JSON dict
    on the last stdout line (docs/collectives.md "Benchmarks & gating").
    """
    import numpy as np

    from ray_tpu.testing import force_cpu_mesh

    force_cpu_mesh(8)
    import jax
    from jax.sharding import Mesh

    from ray_tpu.util.collective.collective import SUM, _store_actor_cls
    from ray_tpu.util.collective.mesh_ops import MeshCollectives

    world, mb = 8, 16
    parts = [
        np.full((mb * 1024 * 1024 // 4,), float(r + 1), dtype=np.float32)
        for r in range(world)
    ]

    # Mesh path: cached staging + one compiled psum program, every call
    # after the first is a single XLA dispatch.
    eng = MeshCollectives(
        Mesh(np.array(jax.devices()[:world]), ("world",)), "world", "perf"
    )
    staged = eng.stage_parts(parts, cache_token="bench")

    def mesh_cycle():
        eng.allreduce(staged, SUM).block_until_ready()

    mesh_rate = timeit("collective allreduce 16MiB (mesh psum)", mesh_cycle)
    mesh_mb_per_s = mb * mesh_rate

    # Store path: the generic backend's data movement — every rank's
    # 16 MiB contribution crosses the object store into the rendezvous
    # actor and the reduced result crosses back out, once per rank.
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        # max_concurrency: in production every rank is a distinct caller so
        # contribute() coroutines interleave; here one driver plays all 8
        # ranks, and per-caller ordering would serialize the rendezvous.
        store = _store_actor_cls().options(max_concurrency=world).remote(world)
        seq = [0]

        def store_cycle():
            s = seq[0]
            seq[0] += 1
            ray_tpu.get(
                [
                    store.contribute.remote(s, r, parts[r], SUM, "allreduce")
                    for r in range(world)
                ],
                timeout=120,
            )

        store_rate = timeit(
            "collective allreduce 16MiB (store actor)", store_cycle
        )
    finally:
        ray_tpu.shutdown()
    store_mb_per_s = mb * store_rate

    print(
        json.dumps(
            {
                "collective_allreduce_mb_per_s": mesh_mb_per_s,
                "collective_allreduce_store_mb_per_s": store_mb_per_s,
                "collective_allreduce_speedup_x": mesh_mb_per_s
                / max(store_mb_per_s, 1e-9),
            }
        )
    )


def _bench_collective_allreduce() -> Dict[str, float]:
    """ICI-native vs store-actor allreduce at 16 MiB per rank, world=8,
    on the forced 8-device CPU mesh (the same topology the collective-xla
    CI job tests). The acceptance bar — mesh >= 2x store — is gated as
    `collective_allreduce_speedup_x` in benchmarks/perf_floors.json."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu._private.ray_perf", "--collective-child"],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"collective bench child failed:\n{out.stdout}\n{out.stderr}"
        )
    line = out.stdout.strip().splitlines()[-1]
    results: Dict[str, float] = json.loads(line)
    for k, v in results.items():
        print(f"{k}: {v:.1f}")
    return results


def _bench_dag_channel() -> float:
    """Compiled-DAG executes/s through a ~1 MiB actor->actor tensor-channel
    edge: producer writes the array into the shm tensor channel, consumer
    reduces it — the steady-state cost of a compiled pipeline hop."""
    import numpy as np

    from ray_tpu import dag

    @ray_tpu.remote
    class Producer:
        def make(self, seed):
            return np.full((512, 512), float(seed), dtype=np.float32)

    @ray_tpu.remote
    class Consumer:
        def total(self, x):
            return float(np.asarray(x)[0, 0])

    p, c = Producer.remote(), Consumer.remote()
    with dag.InputNode() as inp:
        graph = c.total.bind(p.make.bind(inp).with_tensor_transport("tensor"))
    compiled = graph.experimental_compile()
    try:
        assert compiled.execute(3).get() == 3.0  # warm the channel

        n = 50
        seq = [10]

        def cycle():
            base = seq[0]
            seq[0] += n
            for i in range(n):
                assert compiled.execute(base + i).get() == float(base + i)

        return timeit("compiled DAG 1MiB tensor-channel hop", cycle, n)
    finally:
        compiled.teardown()
        for a in (p, c):
            ray_tpu.kill(a)


def main(json_path: str = "") -> Dict[str, float]:
    results: Dict[str, float] = {}
    ray_tpu.init(num_cpus=8, num_tpus=0)

    @ray_tpu.remote
    def trivial():
        return b"ok"

    # Separate sync and async actor classes (reference ray_perf.py does the
    # same): an actor with any coroutine method is an asyncio actor, whose
    # calls all run on the event loop rather than the dedicated exec thread.
    @ray_tpu.remote
    class Counter:
        def small(self):
            return b"ok"

    @ray_tpu.remote
    class AsyncCounter:
        async def asmall(self):
            return b"ok"

    # Warm the worker pool so spawn cost is not measured.
    ray_tpu.get([trivial.remote() for _ in range(16)])

    N = 1000
    results["tasks_sync_per_s"] = timeit(
        "single client tasks sync",
        lambda: [ray_tpu.get(trivial.remote()) for _ in range(100)],
        100,
    )
    results["tasks_async_per_s"] = timeit(
        "single client tasks async (pipelined)",
        lambda: ray_tpu.get([trivial.remote() for _ in range(N)]),
        N,
    )

    actor = Counter.remote()
    ray_tpu.get(actor.small.remote())
    results["actor_calls_sync_per_s"] = timeit(
        "1:1 actor calls sync",
        lambda: [ray_tpu.get(actor.small.remote()) for _ in range(100)],
        100,
    )
    results["actor_calls_async_per_s"] = timeit(
        "1:1 actor calls async (pipelined)",
        lambda: ray_tpu.get([actor.small.remote() for _ in range(N)]),
        N,
    )

    ray_tpu.kill(actor)
    async_actor = AsyncCounter.options(max_concurrency=64).remote()
    ray_tpu.get(async_actor.asmall.remote())
    results["async_actor_calls_per_s"] = timeit(
        "1:1 async actor calls (pipelined)",
        lambda: ray_tpu.get([async_actor.asmall.remote() for _ in range(N)]),
        N,
    )

    ray_tpu.kill(async_actor)
    n_actors = 4
    actors = [Counter.remote() for _ in range(n_actors)]
    ray_tpu.get([a.small.remote() for a in actors])
    results["nn_actor_calls_per_s"] = timeit(
        "n:n actor calls (4 actors, pipelined)",
        lambda: ray_tpu.get(
            [a.small.remote() for _ in range(N // n_actors) for a in actors]
        ),
        N,
    )

    for a in actors:
        ray_tpu.kill(a)
    small = b"x" * 1024
    results["put_small_per_s"] = timeit(
        "1KB put", lambda: [ray_tpu.put(small) for _ in range(500)], 500
    )
    ref = ray_tpu.put(small)
    results["get_small_per_s"] = timeit(
        "1KB get", lambda: [ray_tpu.get(ref) for _ in range(500)], 500
    )

    import numpy as np

    big = np.zeros(16 * 1024 * 1024 // 8)  # 16 MB
    results["put_16mb_per_s"] = timeit(
        "16MB put (shm)", lambda: [ray_tpu.put(big) for _ in range(20)], 20
    )
    bref = ray_tpu.put(big)
    results["get_16mb_per_s"] = timeit(
        "16MB get (zero-copy)", lambda: [ray_tpu.get(bref) for _ in range(50)], 50
    )

    big64 = np.zeros(64 * 1024 * 1024 // 8)  # 64 MB
    results["put_64mb_per_s"] = timeit(
        "64MB put (shm)", lambda: [ray_tpu.put(big64) for _ in range(5)], 5
    )
    del big64

    results["release_batched_per_s"] = _bench_release_batched()
    results["ingest_rows_per_s"] = _bench_ingest()
    results["dag_channel_tensor_per_s"] = _bench_dag_channel()

    ray_tpu.shutdown()

    results["transfer_16mb_per_s"] = _bench_transfer_16mb()
    results.update(_bench_spill())
    results.update(_bench_collective_allreduce())
    results.update(_bench_sched())
    results["gcs_persist_puts_per_s"] = _bench_gcs_persist()
    results["gcs_persist_puts_per_s_replicated"] = _bench_gcs_persist(
        replicated=True, followers=1
    )
    results["gcs_persist_puts_per_s_quorum"] = _bench_gcs_persist(
        replicated=True
    )
    results["gcs_failover_converge_s"] = _bench_gcs_failover()
    results["pubsub_fanout_per_s"] = _bench_pubsub_fanout()
    results["telemetry_overhead_ns"] = _bench_telemetry_overhead()
    results["trace_span_record_ns"] = _bench_trace_span_record()
    if json_path:
        with open(json_path, "w") as f:
            json.dump(results, f, indent=2)
    return results


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--json", default="")
    parser.add_argument(
        "--collective-child",
        action="store_true",
        help="internal: run the collective allreduce bench body "
        "(fresh process so jax sees the forced CPU mesh)",
    )
    args = parser.parse_args()
    if args.collective_child:
        _collective_child_main()
    else:
        main(args.json)
