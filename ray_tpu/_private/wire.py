"""Wire-protocol schema registry for the msgpack RPC layer.

Frames on the wire are ``[msgid, kind, method, payload]`` (rpc.py) and the
payloads are plain msgpack dicts. This registry is the single versioned
description of the payload shape for the high-traffic message types: each
entry declares the keys a producer must send (``required``) and the keys a
consumer may additionally read (``optional``). It has no runtime cost — the
RPC layer never imports it; ``ray_tpu.devtools.rpc_check`` cross-checks
every literal payload dict at call sites and every ``p["k"]``/``p.get("k")``
in handler bodies against it at lint time, so a renamed field fails CI
instead of silently returning ``None`` from ``p.get`` on the other side.

Adding a new RPC method
-----------------------
1. Register the handler (``server.register("MyMethod", ...)``) and add the
   call site.
2. If the method carries a structured payload, add a ``WireSchema`` entry
   here. Required = keys every producer always sends; optional = everything
   any consumer may read. Reply shapes are not checked (replies are built
   and consumed in one file in practice).
3. Run ``python -m ray_tpu.devtools.lint`` — drift in either direction
   (producer missing a required key / sending an undeclared one, consumer
   reading an undeclared one) fails the gate.

Compat story: a key can be *added* by first declaring it ``optional`` and
shipping consumers that ``p.get`` it, then promoting it to ``required``
once every producer sends it. Removal is the reverse. The registry makes
each step reviewable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable


@dataclass(frozen=True)
class WireSchema:
    """Payload-key contract for one RPC method."""

    required: FrozenSet[str] = frozenset()
    optional: FrozenSet[str] = frozenset()


def _s(required: Iterable[str] = (), optional: Iterable[str] = ()) -> WireSchema:
    return WireSchema(frozenset(required), frozenset(optional))


# The top message types by control/data-plane traffic. Methods not listed
# here still get method-name cross-checking, just not key checking.
SCHEMAS: Dict[str, WireSchema] = {
    # -- GCS control plane ---------------------------------------------------
    "RegisterNode": _s(["node_id", "addr", "resources"], ["labels"]),
    "UpdateResources": _s(["node_id", "available"], ["total", "version"]),
    "CreateActor": _s(["spec"], ["wait_alive", "get_if_exists"]),
    "GetActor": _s(["actor_id"]),
    "ReportActorReady": _s(
        ["actor_id"], ["addr", "worker_id", "node_id", "error"]
    ),
    "ReportWorkerDied": _s(["actor_ids"], ["cause", "worker_id"]),
    "KillActor": _s(["actor_id"], ["no_restart"]),
    "KVPut": _s(["key", "value"], ["ns", "overwrite"]),
    "KVGet": _s(["key"], ["ns"]),
    "Subscribe": _s(["channel"]),
    "Publish": _s(["channel", "msg"]),
    # Server->client pubsub delivery push.
    "Pub": _s(["channel", "msg"]),
    # -- raylet scheduling ---------------------------------------------------
    "RequestWorkerLease": _s(
        ["lease_id", "resources"],
        ["strategy", "pg_id", "bundle_index", "spilled_from", "job_id"],
    ),
    "CancelWorkerLease": _s(["lease_id"]),
    "ReturnWorker": _s(["lease_id"], ["dirty"]),
    "LeaseWorkerForActor": _s(["spec"]),
    "KillWorker": _s(["worker_id"], ["probe", "force"]),
    # -- task dispatch -------------------------------------------------------
    "PushTask": _s(["spec"]),
    "PushActorTask": _s(["spec"]),
    # -- object plane --------------------------------------------------------
    "ObjCreate": _s(["oid", "size"], ["pin"]),
    "ObjSeal": _s(["oid"]),
    "WaitObject": _s(["oid"], ["timeout"]),
    "PushStart": _s(["oid", "size"]),
    "PushChunk": _s(["oid", "offset", "data"]),
    # -- logs / observability ------------------------------------------------
    "GetLog": _s([], ["filename", "worker_id", "stream", "tail"]),
}
