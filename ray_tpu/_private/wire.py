"""Wire-protocol schema registry for the msgpack RPC layer.

Frames on the wire are ``[msgid, kind, method, payload]`` — requests may
carry a fifth element, the remaining deadline budget in seconds (rpc.py),
and a sixth, the active trace context as ``[trace_id, span_id]``
(tracing); blob frames (kinds 4/5) carry the sidecar byte length in the
fifth slot instead and never carry trace context — and the payloads are
plain msgpack dicts. This registry is the single
versioned description of the payload shape for the high-traffic message
types: each entry declares the keys a producer must send (``required``),
the keys a consumer may additionally read (``optional``), and the method's
*retry class* — whether the resilience layer may transparently re-issue the
call after a lost connection or timeout. The lint pass
(``ray_tpu.devtools.rpc_check``) cross-checks every literal payload dict at
call sites and every ``p["k"]``/``p.get("k")`` in handler bodies against it,
so a renamed field fails CI instead of silently returning ``None`` from
``p.get`` on the other side; the retry classes are consumed at runtime by
``rpc.RetryableConnection``.

Retry classes
-------------
- ``RETRY_SAFE`` — the handler is an idempotent upsert/read against keyed
  state; re-delivering the request is indistinguishable from delivering it
  once. The resilience layer retries these freely.
- ``RETRY_DEDUP`` — the handler mutates state but dedupes on a msgid-stable
  token carried in the payload (``dedup_key``); e.g. the raylet's
  granted-lease ledger keyed by ``lease_id``. Retried only when the token
  is present in the payload.
- ``RETRY_NONE`` — re-delivery could double-apply (ordered streams,
  one-shot side effects). Failures surface to the caller, whose own
  recovery (task retry, lineage reconstruction) owns the decision.

Adding a new RPC method
-----------------------
1. Register the handler (``server.register("MyMethod", ...)``) and add the
   call site.
2. If the method carries a structured payload, add a ``WireSchema`` entry
   here. Required = keys every producer always sends; optional = everything
   any consumer may read. Declare the retry class honestly: ``RETRY_SAFE``
   is a promise about the handler's semantics, not a convenience flag.
   Reply shapes are not checked (replies are built and consumed in one file
   in practice).
3. Run ``python -m ray_tpu.devtools.lint`` — drift in either direction
   (producer missing a required key / sending an undeclared one, consumer
   reading an undeclared one) fails the gate.

Compat story: a key can be *added* by first declaring it ``optional`` and
shipping consumers that ``p.get`` it, then promoting it to ``required``
once every producer sends it. Removal is the reverse. The registry makes
each step reviewable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, Optional, Tuple

RETRY_SAFE = "safe"
RETRY_DEDUP = "dedup"
RETRY_NONE = "none"

_RETRY_CLASSES = (RETRY_SAFE, RETRY_DEDUP, RETRY_NONE)


_BLOB_DIRECTIONS = (None, "push", "request", "reply")

# The typed-error taxonomy ``errors=`` declarations draw from: the
# RayTpuError family (common.py) plus the RpcError control errors that
# cross the wire *re-typed* (rpc._TYPED_ERRORS prefixes the error-reply
# string with the class name and the caller side reconstructs the class).
# ``__post_init__`` rejects names outside this set so a typo'd declaration
# fails at import, not at the first error. ``DeadlineExceeded`` and
# ``ConnectionLost`` are ambient — the RPC machinery itself can produce
# them for ANY deadlined/disconnected method — so schemas declare only the
# errors their *handler logic* can raise; the exc_flow lint pass
# cross-checks the declarations against each handler closure's actual
# interprocedural escape set.
KNOWN_ERRORS = frozenset(
    {
        "RayTpuError",
        "TaskError",
        "WorkerCrashedError",
        "ActorDiedError",
        "ActorUnavailableError",
        "ObjectLostError",
        "ObjectReconstructionFailedError",
        "GetTimeoutError",
        "TaskCancelledError",
        "PlacementGroupError",
        "CollectiveGroupDiedError",
        "StaleLeaderError",
        "DeadlineExceeded",
    }
)


@dataclass(frozen=True)
class WireSchema:
    """Payload-key contract and retry class for one RPC method.

    ``blob`` marks methods whose bulk bytes travel as a blob sidecar frame
    (kinds 4/5 in rpc.py) instead of a msgpack field: the control frame's
    payload slot carries the declared byte length and the raw bytes follow
    on the stream. Values: ``"push"`` (one-way kind-4 blob to the handler),
    ``"request"`` (kind-4 blob with a msgid, handler sees the bytes as
    ``p["data"]``), ``"reply"`` (the handler returns ``rpc.Blob`` and the
    bytes stream into the caller's registered sink). ``None`` = plain
    control frames only.

    ``trace`` declares whether the method's request frames carry the
    active trace context (frame slot 6, stamped by rpc.py whenever the
    caller has a span active): ``True`` for methods on a request's
    critical path whose handler work belongs inside the trace, ``False``
    for control/background traffic (and for methods whose request travels
    as a kind-4 blob frame, which has no trace slot). Every declared
    schema must pick one — the lint rule ``wire-trace-undeclared`` fails
    on ``None`` so new methods make the choice explicitly.
    """

    required: FrozenSet[str] = frozenset()
    optional: FrozenSet[str] = frozenset()
    retry: str = RETRY_NONE
    dedup_key: Optional[str] = None
    blob: Optional[str] = None
    trace: Optional[bool] = None
    # Typed errors the method's handler logic can raise across the wire
    # (names from KNOWN_ERRORS). An escaping typed error NOT declared here
    # reaches the caller as an untyped RpcError — the exc_flow lint rule
    # ``error-wire-undeclared`` fails on the drift.
    errors: Tuple[str, ...] = ()

    def __post_init__(self):
        if self.retry not in _RETRY_CLASSES:
            raise ValueError(f"unknown retry class {self.retry!r}")
        if self.retry == RETRY_DEDUP and not self.dedup_key:
            raise ValueError("RETRY_DEDUP requires a dedup_key")
        if self.blob not in _BLOB_DIRECTIONS:
            raise ValueError(f"unknown blob direction {self.blob!r}")
        unknown = set(self.errors) - KNOWN_ERRORS
        if unknown:
            raise ValueError(
                f"unknown error name(s) {sorted(unknown)} in errors= "
                "declaration (KNOWN_ERRORS is the taxonomy)"
            )


def _s(
    required: Iterable[str] = (),
    optional: Iterable[str] = (),
    retry: str = RETRY_NONE,
    dedup_key: Optional[str] = None,
    blob: Optional[str] = None,
    trace: Optional[bool] = None,
    errors: Iterable[str] = (),
) -> WireSchema:
    return WireSchema(
        frozenset(required),
        frozenset(optional),
        retry,
        dedup_key,
        blob,
        trace,
        tuple(sorted(errors)),
    )


# The top message types by control/data-plane traffic. Methods not listed
# here still get method-name cross-checking, just not key checking; their
# retry class defaults to the channel's default (RetryableConnection's
# ``default_retry`` — "safe" on the GCS channel, whose handlers are keyed
# upserts/reads by construction).
SCHEMAS: Dict[str, WireSchema] = {
    # -- GCS control plane ---------------------------------------------------
    # "actors" is the hosting report ([{actor_id, worker_id}]) a raylet
    # attaches when re-registering with a restarted GCS: it confirms
    # restored-ALIVE actors without a per-actor probe storm.
    #
    # errors= declares the typed errors a handler can let escape as a typed
    # error reply (exc_flow's error-wire-undeclared rule cross-checks the
    # handlers). GCS methods with a durable write-through declare
    # StaleLeaderError: the replicated store fences writes from a deposed
    # leader (gcs_store.py), and callers dispatch on the typed re-raise to
    # re-resolve the leader. Ambient machinery errors (ConnectionLost,
    # deadline shedding) are not per-method facts and stay undeclared.
    "RegisterNode": _s(
        ["node_id", "addr", "resources"], ["labels", "actors"],
        retry=RETRY_SAFE, trace=False, errors=(),
    ),
    "UpdateResources": _s(
        ["node_id", "available"], ["total", "version"],
        retry=RETRY_SAFE, trace=False, errors=(),
    ),
    # Keyed upsert on actor_id: a retried CreateActor attaches to the
    # existing record instead of double-enqueueing (gcs.py _create_actor).
    "CreateActor": _s(
        ["spec"], ["wait_alive", "get_if_exists"], retry=RETRY_SAFE,
        trace=False, errors=("StaleLeaderError",),
    ),
    "GetActor": _s(["actor_id"], retry=RETRY_SAFE, trace=False, errors=()),
    "ReportActorReady": _s(
        ["actor_id"], ["addr", "worker_id", "node_id", "error"],
        retry=RETRY_SAFE, trace=False, errors=("StaleLeaderError",),
    ),
    "ReportWorkerDied": _s(
        ["actor_ids"], ["cause", "worker_id"], retry=RETRY_SAFE, trace=False,
        errors=("StaleLeaderError",),
    ),
    # Worker-subprocess deadline-enforcement deltas (snapshot-and-reset on
    # the worker side). Deltas are additive, so a blind retry after a lost
    # reply would double-count: RETRY_NONE — a dropped report just folds
    # into the worker's next flush.
    "ReportDeadlineStats": _s(
        ["worker_id", "met", "shed", "enforced", "overruns"],
        retry=RETRY_NONE, trace=False, errors=(),
    ),
    "KillActor": _s(
        ["actor_id"], ["no_restart"], retry=RETRY_SAFE, trace=False,
        errors=("StaleLeaderError",),
    ),
    # NB: a KVPut retry after a lost reply reports added=False on the
    # re-issue when overwrite=False — the effect is still exactly-once.
    "KVPut": _s(
        ["key", "value"], ["ns", "overwrite"], retry=RETRY_SAFE, trace=False,
        errors=("StaleLeaderError",),
    ),
    "KVGet": _s(["key"], ["ns"], retry=RETRY_SAFE, trace=False, errors=()),
    "Subscribe": _s(["channel"], retry=RETRY_SAFE, trace=False, errors=()),
    "Unsubscribe": _s(["channel"], retry=RETRY_SAFE, trace=False, errors=()),
    # Pubsub is at-least-once: a retried Publish may deliver twice.
    "Publish": _s(
        ["channel", "msg"], retry=RETRY_SAFE, trace=False, errors=()
    ),
    # Server->client pubsub delivery push; "seq" is the channel's monotonic
    # publish seqno (gap detection, pubsub.py).
    "Pub": _s(["channel", "msg"], ["seq"], trace=False, errors=()),
    # Per-tick coalesced fan-out: one frame carries every publish on one
    # channel from one flush tick as [channel, msg, seq] triples.
    "PubBatch": _s(["items"], trace=False, errors=()),
    # Channel-state resync for a subscriber that detected a seq gap (its
    # backlog was shed, or it missed a window across a reconnect).
    "Snapshot": _s(["channel"], retry=RETRY_SAFE, trace=False, errors=()),
    # -- HA replication stream (gcs_ha.py standby, docs/fault_tolerance) -----
    # A cross-process standby subscribes to the leader's quorum-acked
    # group-commit stream; the reply carries the (term, seq) watermark the
    # pushes start after.
    "ShipSubscribe": _s([], retry=RETRY_SAFE, trace=False, errors=()),
    # Server->client push of one quorum-acked group commit: raw replicated
    # WAL frames plus the watermark they start after ("prev_seq"; a gap
    # means the standby missed a window and must re-pull ShipSnapshot).
    "ShipFrames": _s(["frames", "term", "seq", "prev_seq"], trace=False, errors=()),
    # Full-state bootstrap/resync of the standby mirror: packed tables at
    # one (term, seq) watermark.
    "ShipSnapshot": _s([], retry=RETRY_SAFE, trace=False, errors=()),
    # -- raylet scheduling ---------------------------------------------------
    # Deduped by the raylet's granted-lease ledger (PR 2): a retried frame
    # with the same lease_id mirrors the original grant outcome.
    "RequestWorkerLease": _s(
        ["lease_id", "resources"],
        ["strategy", "pg_id", "bundle_index", "spilled_from", "job_id",
         "locality"],
        retry=RETRY_DEDUP,
        dedup_key="lease_id",
        trace=True,
        errors=(),
    ),
    "CancelWorkerLease": _s(
        ["lease_id"], retry=RETRY_SAFE, trace=False, errors=()
    ),
    "ReturnWorker": _s(
        ["lease_id"], ["dirty"], retry=RETRY_DEDUP, dedup_key="lease_id",
        trace=False, errors=(),
    ),
    # Per-tick coalesced lease traffic (rpc.call_batched_nowait): one push
    # frame carries every RequestWorkerLease/ReturnWorker/CancelWorkerLease
    # a client issued to one raylet in one event-loop tick, as
    # ``[msgid, method, payload, ttl, trace]`` entries. Entries keep their
    # own msgids, dedup tokens, deadlines, and trace context — the
    # receiving rpc layer re-injects each through normal request dispatch,
    # so retry/dedup semantics are those of the inner methods and the
    # batch frame itself is never retried as a unit. Trace context rides
    # per entry, hence trace=False for the envelope.
    "LeaseBatch": _s(["entries"], trace=False, errors=()),
    # Deduped on spec.actor_id ("actor:<id>" lease ids) via the raylet's
    # actor_creations_in_flight set + grant ledger.
    "LeaseWorkerForActor": _s(
        ["spec"], retry=RETRY_DEDUP, dedup_key="spec", trace=True, errors=()
    ),
    "KillWorker": _s(
        ["worker_id"], ["probe", "force"], retry=RETRY_SAFE, trace=False,
        errors=(),
    ),
    # -- task dispatch (ordered streams: retries owned by the task layer) ----
    # Task failures travel IN the reply payload ({"error": ...}), not as
    # typed error replies — hence no errors= even though tasks fail freely.
    "PushTask": _s(["spec"], trace=True, errors=()),
    "PushActorTask": _s(["spec"], trace=True, errors=()),
    # -- object plane --------------------------------------------------------
    "ObjCreate": _s(
        ["oid", "size"], ["pin"], retry=RETRY_DEDUP, dedup_key="oid",
        trace=True, errors=(),
    ),
    "ObjSeal": _s(["oid"], retry=RETRY_SAFE, trace=True, errors=()),
    "WaitObject": _s(
        ["oid"], ["timeout"], retry=RETRY_SAFE, trace=True, errors=()
    ),
    "PushStart": _s(
        ["oid", "size"], retry=RETRY_DEDUP, dedup_key="oid", trace=True,
        errors=(),
    ),
    # Blob-sidecar data plane: the chunk bytes are NOT a payload key — they
    # follow the control frame on the stream. Blob calls are never
    # transparently retried (the sink may be a live arena span). PushChunk
    # requests ARE kind-4 blob frames, so they cannot carry trace context;
    # FetchChunk requests are plain control frames (only the reply blobs).
    "PushChunk": _s(["oid", "offset"], blob="push", trace=False, errors=()),
    "FetchChunk": _s(
        ["oid", "offset", "size"], blob="reply", trace=True, errors=()
    ),
    # Spill directive: ask a raylet to move named sealed objects to external
    # storage now (owner-driven eviction / pressure tooling). Idempotent —
    # an already-spilled or ineligible oid is reported back, not an error.
    "SpillObjects": _s(["oids"], retry=RETRY_SAFE, trace=False, errors=()),
    # Owner/pull-directed restore: bring one spilled object back into the
    # arena. Restores coalesce on the raylet's restoring-future table, so
    # re-delivery after a lost reply is indistinguishable from one delivery.
    # On a consumer's critical path (pull fallback), hence traced.
    "RestoreSpilled": _s(["oid"], retry=RETRY_SAFE, trace=True, errors=()),
    # Primary-copy pin/unpin: a pinned object is never chosen by the spill
    # scheduler or LRU eviction. Keyed flag write — freely retried.
    "PinObject": _s(["oid"], ["pin"], retry=RETRY_SAFE, trace=False, errors=()),
    # -- ray-client plane ----------------------------------------------------
    # Small puts send "payload" inline; large puts ship the serialized
    # region as a kind-4 blob which the server reads back as "data".
    "CPut": _s([], ["payload", "data"], blob="request", trace=False, errors=()),
    # -- logs / observability ------------------------------------------------
    # Runtime-telemetry flush (telemetry.py flush_delta): counter/histogram
    # deltas plus drained flight-recorder events. Additive like
    # ReportDeadlineStats, so the same RETRY_NONE reasoning applies — an
    # undelivered payload is folded back locally and rides the next flush.
    "ReportTelemetry": _s(
        ["source", "node", "metrics"], ["events"], retry=RETRY_NONE,
        trace=False, errors=(),
    ),
    # Read of the GCS telemetry aggregate (dashboard /metrics).
    "GetTelemetry": _s([], retry=RETRY_SAFE, trace=False, errors=()),
    "GetLog": _s(
        [], ["filename", "worker_id", "stream", "tail"], retry=RETRY_SAFE,
        trace=False, errors=(),
    ),
    # Runtime-span flush (tracing.span_flush_delta): same snapshot-and-reset
    # delta semantics as ReportTelemetry, same RETRY_NONE reasoning.
    "ReportSpans": _s(
        ["source", "node", "spans"], retry=RETRY_NONE, trace=False, errors=()
    ),
    # Server-side-filtered span read: trace_id narrows to one trace, limit
    # bounds the reply — the client never ships the whole span ring.
    "ListSpans": _s(
        [], ["trace_id", "limit"], retry=RETRY_SAFE, trace=False, errors=()
    ),
}


def retry_class(method: str, default: str = RETRY_NONE) -> Tuple[str, Optional[str]]:
    """(retry class, dedup key) for a method; ``default`` for unlisted ones."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return default, None
    return schema.retry, schema.dedup_key


# ---------------------------------------------------------------------------
# Native-codec schema registry (src/fastpath.cc `pack_frame`/`Decoder`).
#
# Methods listed here are packed by the C msgpack encoder on the hot path
# (rpc._pack_frame / rpc.pack_push); everything else — and every frame when
# the .so is absent or RAY_TPU_NATIVE_WIRE=0 — takes the pure-Python
# msgpack path. The encoder is generic (it emits byte-identical msgpack
# for any payload; the parity fuzz in tests/test_fastpath_native.py is the
# proof), so this registry is a *versioning contract*, not a field-layout
# table: ``fields`` mirrors the method's SCHEMAS entry (checked at import
# below) and ``version`` must match the `NATIVE_WIRE_SCHEMA` marker
# compiled into src/fastpath.cc (checked at runtime via
# ``schema_versions()``, and at review time by the rpc_check
# `wire-native-drift` rule). Changing a native method's field list
# therefore forces three synchronized edits — SCHEMAS, this table (fields
# + version bump), and the fastpath.cc marker — or lint fails.
#
# Reply frames reuse the request's method name, so registering a method
# covers its replies too (the "lease replies" of the grant fan-out path).
# ---------------------------------------------------------------------------

NATIVE_WIRE_SCHEMAS: Dict[str, Tuple[int, Tuple[str, ...]]] = {
    "RequestWorkerLease": (1, (
        "bundle_index", "job_id", "lease_id", "locality", "pg_id",
        "resources", "spilled_from", "strategy",
    )),
    "ReturnWorker": (1, ("dirty", "lease_id")),
    "CancelWorkerLease": (1, ("lease_id",)),
    "LeaseBatch": (1, ("entries",)),
    "PubBatch": (1, ("items",)),
}

for _m, (_v, _fields) in NATIVE_WIRE_SCHEMAS.items():
    _schema = SCHEMAS.get(_m)
    if _schema is None:
        raise AssertionError(f"native wire schema {_m!r} missing from SCHEMAS")
    _declared = tuple(sorted(_schema.required | _schema.optional))
    if tuple(sorted(_fields)) != _declared:
        raise AssertionError(
            f"NATIVE_WIRE_SCHEMAS[{_m!r}] fields {sorted(_fields)} drifted "
            f"from SCHEMAS {list(_declared)}: update the fields tuple, bump "
            "its version here, and bump the matching NATIVE_WIRE_SCHEMA "
            "marker in src/fastpath.cc"
        )
del _m, _v, _fields, _schema, _declared


def native_method_set(native_mod=None) -> FrozenSet[str]:
    """Methods eligible for native pack on this process.

    With ``native_mod`` (the loaded ``_fastpath`` module), only methods
    whose compiled schema version matches this registry qualify — a stale
    .so silently falls back per-method instead of shipping frames packed
    under an outdated contract. With ``native_mod=None`` (no .so), the
    full declared set is returned so the caller can still count fallback
    packs against it."""
    if native_mod is None:
        return frozenset(NATIVE_WIRE_SCHEMAS)
    try:
        versions = native_mod.schema_versions()
    except Exception:
        return frozenset()
    return frozenset(
        m for m, (v, _f) in NATIVE_WIRE_SCHEMAS.items()
        if versions.get(m) == v
    )
