"""Worker process entrypoint: executes tasks and hosts actors.

Analog of the reference's default_worker.py + the Cython task-execution handler
(python/ray/_raylet.pyx:2251 execute_task path): the asyncio loop owns RPC; user
task code runs on executor threads (sync) or directly on the loop (async actor
methods). Ordered actor execution follows the per-caller sequence-number design
of the reference's ActorSchedulingQueue.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import ctypes
import inspect
import logging
import os
import sys
import threading
import time
import traceback
from collections import OrderedDict
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import rpc, serialization, telemetry
from ray_tpu._private.common import TaskError, TaskSpec, config
from ray_tpu._private.core_worker import CoreWorker, ObjectRef
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

# Max unacked streamed generator items in flight to the owner (reference:
# _generator_backpressure_num_objects).
_GEN_BACKPRESSURE_WINDOW = 16


def _deadline_stats_delta(worker_id: str) -> Optional[dict]:
    """Snapshot-and-reset the process deadline counters as a wire delta.

    Runs on the event loop with no awaits between read and reset, so no
    enforcement event can land in the gap and be lost or double-counted.
    Returns None when there is nothing to report.
    """
    st = rpc.deadline_stats
    if not (st.met or st.shed or st.enforced or st.overruns):
        return None
    delta = {
        "met": st.met,
        "shed": st.shed,
        "enforced": st.enforced,
        "overruns": [[m, float(late)] for m, late in st.overruns],
        "worker_id": worker_id,
    }
    st.reset()
    return delta


def _restore_deadline_delta(delta: dict) -> None:
    """Fold an undelivered delta back into the local counters so the next
    flush carries it. If the report actually landed and only the reply was
    lost, counters double-count (ReportDeadlineStats is RETRY_NONE for the
    same reason) — acceptable for telemetry, and an overrun re-reported
    twice still flags the same real violation."""
    st = rpc.deadline_stats
    st.met += delta["met"]
    st.shed += delta["shed"]
    st.enforced += delta["enforced"]
    st.overruns.extend((m, late) for m, late in delta["overruns"])


class _ExecThread:
    """Dedicated execution thread with reply batching.

    The task/actor hot path never crosses loop<->thread per call the way
    run_in_executor does: the RPC layer enqueues work items straight from
    data_received (sync handler), the thread executes back-to-back, and
    completed replies are flushed to the event loop in coalesced batches
    (one call_soon_threadsafe per burst). Analog of the reference's
    dedicated actor-scheduling-queue execution thread
    (transport/actor_scheduling_queue.cc).
    """

    def __init__(self, executor: "Executor", loop: asyncio.AbstractEventLoop):
        import queue

        self.executor = executor
        self.loop = loop
        self.q: "queue.SimpleQueue" = queue.SimpleQueue()
        self.replies: list = []
        self._reply_wake = False
        self.thread = threading.Thread(
            target=self._run, name="ray_tpu_exec", daemon=True
        )
        self.thread.start()

    def submit(self, conn, msgid: int, method: str, wire: dict) -> None:
        self.q.put((conn, msgid, method, wire))

    def _run(self) -> None:
        ex = self.executor
        while True:
            item = self.q.get()
            if item is None:
                return
            conn, msgid, method, wire = item
            task_id = wire.get("task_id", "")
            if task_id in ex.cancelled_tasks:
                ex.cancelled_tasks.pop(task_id, None)
                from ray_tpu._private.common import TaskCancelledError

                self.replies.append(
                    (conn, msgid, method,
                     {"error": ex._error_payload(TaskCancelledError("task cancelled"))})
                )
                if not self._reply_wake:
                    self._reply_wake = True
                    self.loop.call_soon_threadsafe(self._drain_replies)
                continue
            track = ex.running_tasks[task_id] = {
                "thread_id": threading.get_ident(),
                "async_task": None,
            }
            try:
                payload = ex._execute_sync(wire, conn)
            except BaseException as e:  # noqa: BLE001 - serialize any failure
                if isinstance(e, SystemExit):
                    self.loop.call_soon_threadsafe(
                        self.loop.call_later, 0.1, os._exit, 0
                    )
                    payload = {
                        "error": ex._error_payload(RuntimeError("actor exited"))
                    }
                else:
                    payload = {"error": ex._error_payload(e)}
            finally:
                ex.running_tasks.pop(wire.get("task_id", ""), None)
            self.replies.append((conn, msgid, method, payload))
            if not self._reply_wake:
                self._reply_wake = True
                self.loop.call_soon_threadsafe(self._drain_replies)

    def _drain_replies(self) -> None:
        self._reply_wake = False
        batch, self.replies = self.replies, []
        for conn, msgid, method, payload in batch:
            conn.reply_nowait(msgid, method, payload)

    def run_on_loop(self, coro):
        """Blockingly run a coroutine on the event loop (slow aspects of an
        otherwise thread-executed call: ref resolution, plasma writes)."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result()


class Executor:
    """Task/actor execution engine wired onto a CoreWorker."""

    def __init__(self, core: CoreWorker):
        self.core = core
        self.fn_cache: Dict[str, Any] = {}
        self.actor_instance: Any = None
        self.actor_spec: Optional[dict] = None
        self.pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        # Per-caller ordered execution state for the sync single-concurrency
        # actor path (reference: sequential_actor_submit_queue.cc).
        self.expected_seq: Dict[str, int] = {}
        self.pending_seq: Dict[str, Dict[int, asyncio.Future]] = {}
        self.exec_lock = asyncio.Lock()
        # task_id -> {"thread_id": int|None, "async_task": Task|None}
        self.running_tasks: Dict[str, dict] = {}
        self._exec_thread: Optional[_ExecThread] = None
        # True when the hosted actor has no coroutine methods (set at
        # creation); gates the exec-thread fast path.
        self.actor_all_sync = False
        # Concurrency groups (set at actor creation when declared).
        self.cgroup_sems = None
        self.cgroup_pools = None
        # Tasks cancelled before they started executing (they may still be
        # queued behind a running task on this worker — pipelined dispatch).
        # Bounded: best-effort markers for races with finished tasks must not
        # accumulate forever.
        self.cancelled_tasks: "OrderedDict[str, None]" = OrderedDict()
        # Event loop handle for the native fastpath callback's plasma hop.
        self._fp_loop: Optional[asyncio.AbstractEventLoop] = None
        core.server.register("PushTask", self.handle_push_task)
        core.server.register("PushActorTask", self.handle_push_actor_task)
        core.server.register("CreateActor", self.handle_create_actor)
        core.server.register("CancelTask", self.handle_cancel_task)
        core.server.register("Exit", self.handle_exit)
        core.server.register_sync("PushTask", self._sync_push_task)
        core.server.register_sync("PushActorTask", self._sync_push_actor_task)

    # -- native fastpath (ray_tpu._native._fastpath server callback) ---------

    def fastpath_exec(self, tid: bytes, fid: bytes, name: bytes, blob: bytes):
        """Execute one task for the native direct-call channel.

        Runs on the extension's connection thread with the GIL held (the
        C++ side serializes execution per connection, matching the sync
        exec-thread semantics). Statuses: 0 ok (payload = inline serialized
        value), 1 error (payload = serialized exception), 4 function not
        cached here (driver re-sends via the RPC path, which populates the
        cache), 6 large result stored in plasma (payload = pickled returns
        descriptor).
        """
        import pickle

        from ray_tpu._private.ids import return_object_ids

        try:
            fn = self.fn_cache.get(fid.decode())
            if fn is None or asyncio.iscoroutinefunction(fn):
                # Unknown here, or a coroutine function (needs the event
                # loop): the driver re-sends via the RPC path.
                return (4, b"")
            with serialization.DeserializationContext(
                ref_deserializer=self.core._deserialize_ref
            ):
                (args, kwargs), _ = serialization.deserialize(blob)
            result = fn(*args, **kwargs)
            serialized = serialization.serialize(result)
            if serialized.total_size <= config.max_direct_call_object_size:
                # bytes() wrap: the C++ side reads the payload with
                # PyBytes_AsStringAndSize, which rejects bytearray.
                return (0, bytes(serialized.to_bytes()))
            # Large return: plasma write via the worker loop, then the same
            # returns descriptor the RPC path uses.
            oid = return_object_ids(tid.decode(), 1)[0]
            asyncio.run_coroutine_threadsafe(
                self.core.plasma.put_serialized(oid, serialized),
                self._fp_loop,
            ).result(timeout=60)
            return (6, pickle.dumps({"plasma": list(self.core.raylet_addr)}))
        except BaseException as e:  # noqa: BLE001 - must serialize any failure
            return (1, self._error_payload(e))

    # -- sync fast-path dispatch (called inline from data_received) ----------

    def _exec(self) -> _ExecThread:
        t = self._exec_thread
        if t is None:
            t = self._exec_thread = _ExecThread(self, asyncio.get_running_loop())
        return t

    def _fallback_async(self, conn, msgid, method, handler, payload) -> None:
        async def run():
            try:
                result = await handler(conn, payload)
            except Exception as e:
                conn.reply_error_nowait(
                    msgid, method, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                )
                return
            conn.reply_nowait(msgid, method, result)

        rpc.spawn(run())

    def _sync_push_actor_task(self, conn, msgid, p) -> None:
        wire = p["spec"]
        if (
            self.actor_all_sync
            and self.cgroup_sems is None
            and self.actor_instance is not None
            and (self.actor_spec or {}).get("max_concurrency", 1) == 1
            and wire.get("actor_method") != "__rt_dag_loop__"
        ):
            # Ordered all-sync actor: every call funnels through the exec
            # thread in arrival order (= per-caller seq order), which enforces
            # the sequencing the async path needed futures for. Actors with
            # coroutine methods stay on the loop path — their awaits must
            # interleave across callers (e.g. rendezvous patterns).
            # Advance the async path's seq ledger now: a later call routed
            # through handle_push_actor_task (__rt_dag_loop__, restarts) must
            # not wait on a turn the exec thread will never signal.
            seq = wire.get("seq_no", -1)
            if seq >= 0:
                self._advance_seq(wire.get("caller_id") or "anon", seq)
            self._exec().submit(conn, msgid, "PushActorTask", wire)
            return
        self._fallback_async(conn, msgid, "PushActorTask", self.handle_push_actor_task, p)

    def _sync_push_task(self, conn, msgid, p) -> None:
        wire = p["spec"]
        fn = self.fn_cache.get(wire.get("func_id"))
        renv = wire.get("runtime_env") or {}
        if (
            fn is not None
            and not asyncio.iscoroutinefunction(fn)
            and wire.get("args_blob") is not None
            and not wire.get("ref_positions")
            and not wire.get("kw_ref_keys")
            and wire.get("num_returns") != -1
            and not renv.get("working_dir")
            and not renv.get("py_modules")
            and not renv.get("pip")
            and not renv.get("conda")
        ):
            self._exec().submit(conn, msgid, "PushTask", wire)
            return
        self._fallback_async(conn, msgid, "PushTask", self.handle_push_task, p)

    def _execute_sync(self, wire: dict, conn=None):
        """Run one task/actor call on the exec thread; returns the reply
        payload. Slow aspects (ref args, plasma-resident args/returns) hop to
        the event loop via run_on_loop."""
        core = self.core
        profile = config.task_profile_events
        t0 = time.time()
        exec_t = self._exec_thread
        actor_method = wire.get("actor_method")
        if actor_method is not None:
            fn = getattr(self.actor_instance, actor_method)
        else:
            fn = self.fn_cache[wire["func_id"]]
        # -- arguments
        if (
            wire.get("args_blob") is not None
            and not wire.get("ref_positions")
            and not wire.get("kw_ref_keys")
        ):
            with serialization.DeserializationContext(
                ref_deserializer=core._deserialize_ref
            ):
                (args, kwargs), _ = serialization.deserialize(wire["args_blob"])
        else:
            t_fetch = time.time()
            args, kwargs = exec_t.run_on_loop(self.load_args(wire))
            if "trace_ctx" in wire:
                tracing.record_span(
                    "task.arg_fetch",
                    "arg_fetch",
                    t_fetch,
                    time.time() - t_fetch,
                    ctx=tracing.ctx_from_wire(wire),
                    task_id=wire["task_id"],
                )
        t_args = time.time()
        # -- execute
        renv = wire.get("runtime_env") or {}
        env_vars = renv.get("env_vars")
        # Manual scope: it must stay open through the generator-drain branch
        # below (a streaming task's body runs during iteration, not at
        # fn() time), so nested submits keep the trace context and the
        # execute span covers real execution. Gated on the wire key so the
        # disabled case costs one dict lookup (this is the 10k+ tasks/s
        # fast path).
        trace_scope = (
            tracing.execute_scope(core, wire) if "trace_ctx" in wire else None
        )
        if trace_scope is not None:
            trace_scope.__enter__()
        try:
            if env_vars:
                from ray_tpu.runtime_env.context import scoped_env_vars

                with scoped_env_vars(env_vars):
                    result = (
                        exec_t.run_on_loop(fn(*args, **kwargs))
                        if asyncio.iscoroutinefunction(fn)
                        else fn(*args, **kwargs)
                    )
            elif asyncio.iscoroutinefunction(fn):
                result = exec_t.run_on_loop(fn(*args, **kwargs))
            else:
                result = fn(*args, **kwargs)
            t_exec = time.time()
            # -- returns (inside the trace scope: generator bodies run here)
            reply, t_exec = self._sync_returns(wire, result, conn, t_exec)
        finally:
            if trace_scope is not None:
                trace_scope.__exit__(None, None, None)
        if profile:
            # Per-task phase spans (reference: worker profile events in the
            # chrome timeline, RAY_PROFILING + profiling.py).
            core.record_task_event(
                wire["task_id"],
                wire["name"],
                "PROFILE",
                start=t0,
                phases={
                    "deserialize_args": t_args - t0,
                    "execute": t_exec - t_args,
                    "store_returns": time.time() - t_exec,
                },
            )
        return reply

    def _sync_returns(self, wire: dict, result, conn, t_exec):
        """Store the result(s) of an exec-thread call; returns (reply,
        t_exec). Runs INSIDE the trace scope: a streaming generator's body
        executes during the drain loop here, not at fn() time."""
        exec_t = self._exec_thread
        num_returns = wire["num_returns"]
        if num_returns == 0:
            return {"returns": []}, t_exec
        if num_returns == -1 and inspect.isgenerator(result):
            # Streaming generator on the exec thread: store + push each item
            # as produced (same GeneratorItem protocol as the async path).
            # Window of unacked pushes bounds the owner's buffering when the
            # consumer is slower than the producer (reference:
            # _generator_backpressure_num_objects).
            idx = 0
            inflight: list = []
            for item in result:
                ret = self._store_one_sync(self._dyn_oid(wire, idx), item)
                fut = asyncio.run_coroutine_threadsafe(
                    self._send_generator_item(
                        conn, wire["task_id"], idx, ret[0]
                    ),
                    exec_t.loop,
                )
                inflight.append(fut)
                if len(inflight) >= _GEN_BACKPRESSURE_WINDOW:
                    for f in inflight:
                        f.result()  # acks double as flow-control tokens
                    inflight = []
                idx += 1
            for f in inflight:
                f.result()
            # Generator execution IS the drain; restate t_exec so the
            # PROFILE store_returns phase doesn't swallow it.
            return {"dynamic_count": idx}, time.time()
        if num_returns == -1:
            num_returns = 1
        values = [result] if num_returns == 1 else list(result)
        if num_returns != 1 and len(values) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{len(values)}"
            )
        out = []
        for oid, value in zip(wire["return_ids"], values):
            out.extend(self._store_one_sync(oid, value))
        return {"returns": out}, t_exec

    def _store_one_sync(self, oid: str, value) -> list:
        serialized = serialization.serialize(value)
        if serialized.total_size <= config.max_direct_call_object_size:
            return [{"inline": serialized.to_bytes()}]
        self._exec_thread.run_on_loop(self.core.plasma.put_serialized(oid, serialized))
        return [{"plasma": list(self.core.raylet_addr)}]

    # -- function table ------------------------------------------------------

    async def get_function(self, func_id: str):
        fn = self.fn_cache.get(func_id)
        if fn is None:
            blob = await self.core.gcs.kv_get(func_id, ns="fn")
            if blob is None:
                raise rpc.RpcError(f"function {func_id} not found in GCS")
            fn = cloudpickle.loads(blob)
            self.fn_cache[func_id] = fn
        return fn

    # -- argument loading ----------------------------------------------------

    async def load_args(self, wire: dict):
        if wire.get("args_object"):
            ref = ObjectRef(
                wire["args_object"],
                tuple(wire["owner_addr"]) if wire.get("owner_addr") else None,
                self.core,
            )
            # Task-argument fetches are below interactive gets in the pull
            # admission order (reference: pull_manager.h bundle priority).
            payload = await self.core._resolve_payload(ref, None, purpose="task_arg")
        else:
            payload = wire["args_blob"]
        with serialization.DeserializationContext(
            ref_deserializer=self.core._deserialize_ref
        ):
            (args, kwargs), _ = serialization.deserialize(payload)
        args = list(args)
        # Resolve top-level ObjectRef args to values (reference semantics).
        for i in wire.get("ref_positions") or []:
            args[i] = await self.core.get_objects(args[i], timeout=None)
        for k in wire.get("kw_ref_keys") or []:
            kwargs[k] = await self.core.get_objects(kwargs[k], timeout=None)
        return args, kwargs

    # -- result storage ------------------------------------------------------

    async def store_returns(self, spec_wire: dict, result: Any) -> list:
        num_returns = spec_wire["num_returns"]
        if num_returns == 0:
            return []
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned {len(values)}"
                )
        out = []
        for oid, value in zip(spec_wire["return_ids"], values):
            serialized = serialization.serialize(value)
            if serialized.total_size <= config.max_direct_call_object_size:
                out.append({"inline": serialized.to_bytes()})
            else:
                await self.core.plasma.put_serialized(oid, serialized)
                out.append({"plasma": list(self.core.raylet_addr)})
        return out

    def _error_payload(self, exc: BaseException) -> bytes:
        # Exact bytes required: this payload can cross the native fastpath
        # channel (PyBytes_AsStringAndSize rejects bytearray).
        tb = traceback.format_exc()
        try:
            exc.task_traceback = tb  # best effort annotation
        except Exception:
            pass
        try:
            return bytes(serialization.serialize(exc).to_bytes())
        except Exception:
            return bytes(
                serialization.serialize(
                    TaskError(RuntimeError(repr(exc)), traceback_str=tb)
                ).to_bytes()
            )

    # -- normal tasks --------------------------------------------------------

    async def handle_push_task(self, conn, p):
        wire = p["spec"]
        task_id = wire.get("task_id", "")
        if task_id in self.cancelled_tasks:
            self.cancelled_tasks.pop(task_id, None)
            from ray_tpu._private.common import TaskCancelledError

            return {"error": self._error_payload(TaskCancelledError("task cancelled"))}
        track = self.running_tasks[task_id] = {"thread_id": None, "async_task": None}
        try:
            renv = wire.get("runtime_env") or {}
            if (
                renv.get("working_dir") or renv.get("py_modules")
                or renv.get("pip") or renv.get("conda")
            ):
                # Shared worker process: packages and pip-env site-packages
                # go on sys.path (idempotent) but the cwd is left alone; env
                # vars are call-scoped below.
                from ray_tpu.runtime_env.context import apply_runtime_env

                await apply_runtime_env(
                    self.core,
                    {
                        k: renv[k]
                        for k in ("working_dir", "py_modules", "pip", "conda")
                        if k in renv
                    },
                    chdir=False,
                )
            profile = config.task_profile_events
            t0 = time.time()
            fn = await self.get_function(wire["func_id"])
            args, kwargs = await self.load_args(wire)
            t_args = time.time()
            if "trace_ctx" in wire:
                tracing.record_span(
                    "task.arg_fetch",
                    "arg_fetch",
                    t0,
                    t_args - t0,
                    ctx=tracing.ctx_from_wire(wire),
                    task_id=task_id,
                )
            from ray_tpu.runtime_env.context import scoped_env_vars

            with scoped_env_vars(renv.get("env_vars")), tracing.execute_scope(
                self.core, wire
            ):
                tctx = tracing.current_context()
                if task_id in self.cancelled_tasks:
                    # Cancel arrived while args/function were being resolved.
                    self.cancelled_tasks.pop(task_id, None)
                    from ray_tpu._private.common import TaskCancelledError

                    raise asyncio.CancelledError("task cancelled")
                if asyncio.iscoroutinefunction(fn):
                    coro_task = rpc.spawn(fn(*args, **kwargs))
                    track["async_task"] = coro_task
                    result = await coro_task
                else:
                    loop = asyncio.get_running_loop()

                    def run_tracked():
                        if task_id in self.cancelled_tasks:
                            self.cancelled_tasks.pop(task_id, None)
                            from ray_tpu._private.common import TaskCancelledError

                            raise TaskCancelledError("task cancelled")
                        track["thread_id"] = threading.get_ident()
                        # Trace context does not cross run_in_executor.
                        tok = tracing.set_context(tctx)
                        try:
                            return fn(*args, **kwargs)
                        finally:
                            tracing.reset_context(tok)
                            track["thread_id"] = None

                    result = await loop.run_in_executor(self.pool, run_tracked)
                if wire["num_returns"] == -1 and inspect.isgenerator(result):
                    # Streaming generator: each yielded item is stored and
                    # reported to the owner AS PRODUCED, so the consumer's
                    # iteration overlaps this producer (reference:
                    # ReportGeneratorItemReturns). Runs INSIDE the trace
                    # scope: the generator body executes during this drain.
                    # Acked window = flow control (see _GEN_BACKPRESSURE_WINDOW).
                    idx = 0
                    loop = asyncio.get_running_loop()

                    def _advance():
                        tok = tracing.set_context(tctx)
                        try:
                            return True, next(result)
                        except StopIteration:
                            return False, None
                        finally:
                            tracing.reset_context(tok)

                    inflight = []
                    while True:
                        ok, item = await loop.run_in_executor(self.pool, _advance)
                        if not ok:
                            break
                        ret = await self.store_returns(
                            {"num_returns": 1,
                             "return_ids": [self._dyn_oid(wire, idx)]},
                            item,
                        )
                        inflight.append(rpc.spawn(
                            self._send_generator_item(
                                conn, wire["task_id"], idx, ret[0]
                            )
                        ))
                        if len(inflight) >= _GEN_BACKPRESSURE_WINDOW:
                            await asyncio.gather(*inflight)
                            inflight = []
                        idx += 1
                    if inflight:
                        await asyncio.gather(*inflight)
                    if profile:
                        self._record_profile(wire, t0, t_args, t_args)
                    return {"dynamic_count": idx}
            t_exec = time.time()
            returns = await self.store_returns(wire, result)
            if profile:
                self._record_profile(wire, t0, t_args, t_exec)
            return {"returns": returns}
        except asyncio.CancelledError:
            from ray_tpu._private.common import TaskCancelledError

            return {"error": self._error_payload(TaskCancelledError("task cancelled"))}
        except BaseException as e:  # noqa: BLE001 - must serialize any failure
            logger.info("task %s raised: %r", wire.get("name"), e)
            return {"error": self._error_payload(e)}
        finally:
            self.running_tasks.pop(task_id, None)

    def _record_profile(self, wire: dict, t0: float, t_args: float, t_exec: float) -> None:
        """One PROFILE task event with phase durations (reference:
        RAY_PROFILING worker profile events)."""
        self.core.record_task_event(
            wire["task_id"],
            wire.get("name", "task"),
            "PROFILE",
            start=t0,
            phases={
                "deserialize_args": t_args - t0,
                "execute": t_exec - t_args,
                "store_returns": time.time() - t_exec,
            },
        )

    @staticmethod
    def _dyn_oid(wire: dict, index: int) -> str:
        from ray_tpu._private.ids import TaskID, deterministic_object_id

        return deterministic_object_id(
            TaskID.from_hex(wire["task_id"]), index + 1
        ).hex()

    async def handle_cancel_task(self, conn, p):
        """Cancel a running task: async tasks via asyncio cancellation, sync
        tasks via an exception raised in the executing thread (the reference
        raises KeyboardInterrupt in the worker; same best-effort semantics —
        blocking C calls are not interrupted until they return)."""
        from ray_tpu._private.common import TaskCancelledError

        track = self.running_tasks.get(p["task_id"])
        if track is None or (
            track.get("async_task") is None and track.get("thread_id") is None
        ):
            # Not executing yet: queued behind the current task (pipelined
            # push) or waiting for the executor. Mark it so execution is
            # skipped when its turn comes.
            self.cancelled_tasks[p["task_id"]] = None
            while len(self.cancelled_tasks) > 1024:
                self.cancelled_tasks.popitem(last=False)
            return {"found": True, "queued": True}
        if track.get("async_task") is not None:
            track["async_task"].cancel()
            return {"found": True}
        tid = track.get("thread_id")
        if tid is not None:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(TaskCancelledError)
            )
            return {"found": True}
        return {"found": False}

    # -- actors --------------------------------------------------------------

    async def handle_create_actor(self, conn, p):
        wire = p["spec"]
        self.actor_spec = wire
        # Stash the hosted actor's identity on the CoreWorker so library code
        # running inside this process (collective group membership, death
        # watches) can learn "which actor am I" without an RPC.
        self.core.current_actor_id = wire.get("actor_id")
        max_c = wire.get("max_concurrency") or 1
        cgroups = wire.get("concurrency_groups")
        if cgroups:
            # Per-method concurrency groups (reference:
            # transport/concurrency_group_manager.cc): each group gets its
            # own semaphore (async methods) and thread pool (sync methods);
            # calls in different groups never block each other. Ungrouped
            # calls ride the default group sized by max_concurrency.
            if max_c == 1:
                max_c = 1000  # reference default for concurrency-group actors
            self.cgroup_sems = {
                name: asyncio.Semaphore(int(n)) for name, n in cgroups.items()
            }
            self.cgroup_sems["_default"] = asyncio.Semaphore(max_c)
            self.cgroup_pools = {
                name: concurrent.futures.ThreadPoolExecutor(max_workers=int(n))
                for name, n in cgroups.items()
            }
            self.cgroup_pools["_default"] = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_c
            )
        if max_c > 1 and not cgroups:
            self.pool = concurrent.futures.ThreadPoolExecutor(max_workers=max_c)
        # Run the constructor in the background and reply to the raylet NOW.
        # The raylet's lease grant (and through it the GCS actor scheduler)
        # must not block on user __init__: constructors may legitimately
        # rendezvous with actors that haven't been placed yet (collective
        # group bootstrap), and serializing placement behind them deadlocks.
        # Readiness/failure flows to the GCS via ReportActorReady, which is
        # what gates task submission (reference: GcsActorScheduler pushes the
        # creation task asynchronously and tracks readiness separately).
        self._creation_task = rpc.spawn(self._run_actor_creation(wire))
        return {"ok": True}

    async def _run_actor_creation(self, wire) -> None:
        try:
            if wire.get("runtime_env"):
                # Actors own their process: permanent application (env vars,
                # working_dir chdir + sys.path, py_modules).
                from ray_tpu.runtime_env.context import apply_runtime_env

                await apply_runtime_env(self.core, wire["runtime_env"])
            cls = await self.get_function(wire["func_id"])
            args, kwargs = await self.load_args(wire)
            loop = asyncio.get_running_loop()
            tctx = tracing.ctx_from_wire(wire) or tracing.current_context()

            def _construct():
                # Trace context does not cross run_in_executor; re-set it so
                # work submitted from __init__ joins the creation trace.
                tok = tracing.set_context(tctx)
                try:
                    return cls(*args, **kwargs)
                finally:
                    tracing.reset_context(tok)

            self.actor_instance = await loop.run_in_executor(self.pool, _construct)
            self.actor_all_sync = not any(
                asyncio.iscoroutinefunction(m)
                for _, m in inspect.getmembers(
                    type(self.actor_instance), callable
                )
            )
            await self._report_actor_ready(
                {
                    "actor_id": wire["actor_id"],
                    "addr": list(self.core.addr),
                    "worker_id": self.core.worker_id,
                    "node_id": self.core.node_id,
                }
            )
        except asyncio.CancelledError:
            # Teardown cancellation is not a creation failure: unwind so the
            # raylet's worker-death report drives the actor FSM instead of a
            # bogus "creation failed" report pinning the actor DEAD.
            raise
        except BaseException as e:
            logger.exception("actor creation failed")
            await self._report_actor_ready(
                {
                    "actor_id": wire["actor_id"],
                    "error": f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
                }
            )

    async def _report_actor_ready(self, payload: dict) -> None:
        """Deliver the readiness/failure report, retrying through GCS blips.
        This is the ONLY signal that moves the actor out of PENDING_CREATION
        (the creation task is otherwise unobserved), so if it cannot be
        delivered the worker exits: the raylet's worker-death report then
        fails/restarts the actor instead of leaving callers blocked forever."""
        for attempt in range(5):
            try:
                await self.core.gcs.call("ReportActorReady", payload)
                return
            # This bounded retry loop IS the StaleLeaderError handling: the
            # gcs channel re-resolves the leader on reconnect, and after 5
            # failures the worker exits so the raylet surfaces the failure —
            # nothing is converted to silent success.
            except Exception:  # exc-flow: disable=swallowed-control-error
                logger.exception(
                    "ReportActorReady attempt %d/5 failed", attempt + 1
                )
                await asyncio.sleep(min(2.0**attempt, 10.0))
        logger.error(
            "could not report actor %s readiness; exiting so the raylet "
            "surfaces the failure",
            payload.get("actor_id", "?")[:8],
        )
        os._exit(1)

    async def handle_push_actor_task(self, conn, p):
        wire = p["spec"]
        caller = wire.get("caller_id") or "anon"
        seq = wire.get("seq_no", -1)
        if self.cgroup_sems is not None:
            # Concurrency-group actor: out-of-order execution, bounded per
            # group (reference: out_of_order_actor_submit_queue.cc +
            # concurrency_group_manager.cc).
            group = wire.get("concurrency_group") or "_default"
            sem = self.cgroup_sems.get(group)
            if sem is None:
                raise rpc.RpcError(f"unknown concurrency group {group!r}")
            if seq >= 0:
                self._advance_seq(caller, seq)
            async with sem:
                return await self._run_actor_method(
                    wire, pool=self.cgroup_pools[group], conn=conn
                )
        ordered = (self.actor_spec or {}).get("max_concurrency", 1) == 1
        if ordered and seq >= 0:
            await self._wait_my_turn(caller, seq)
        try:
            return await self._run_actor_method(wire, conn=conn)
        finally:
            if ordered and seq >= 0:
                self._advance_seq(caller, seq)

    async def _wait_my_turn(self, caller: str, seq: int) -> None:
        expected = self.expected_seq.get(caller, 0)
        if seq <= expected:
            return
        fut = asyncio.get_running_loop().create_future()
        self.pending_seq.setdefault(caller, {})[seq] = fut
        # Resolved by _advance_seq when the predecessor finishes (its
        # finally runs even on failure); mirrors the reference
        # out-of-order submit queue, where sequencing waits are unbounded
        # and the caller's task-level retry owns recovery.
        await fut  # rpc-flow: disable=unbounded-await

    def _advance_seq(self, caller: str, seq: int) -> None:
        nxt = max(self.expected_seq.get(caller, 0), seq + 1)
        self.expected_seq[caller] = nxt
        pending = self.pending_seq.get(caller, {})
        if nxt in pending:
            fut = pending.pop(nxt)
            if not fut.done():
                fut.set_result(None)

    async def _run_actor_method(self, wire: dict, pool=None, conn=None):
        if pool is None:
            pool = self.pool
        try:
            if self.actor_instance is None:
                raise RuntimeError("actor not initialized")
            if wire["actor_method"] == "__rt_dag_loop__":
                # Compiled-DAG resident loop (ray_tpu.dag): runs until the
                # driver writes the STOP sentinel into the input channels.
                from ray_tpu.dag.exec_loop import dag_exec_loop

                args, kwargs = await self.load_args(wire)
                loop = asyncio.get_running_loop()
                dag_tctx = tracing.ctx_from_wire(wire) or tracing.current_context()

                def _dag_run():
                    # Trace context does not cross run_in_executor; re-set it
                    # so submissions from inside the DAG loop stay traced.
                    tok = tracing.set_context(dag_tctx)
                    try:
                        return dag_exec_loop(self.actor_instance, *args)
                    finally:
                        tracing.reset_context(tok)

                result = await loop.run_in_executor(None, _dag_run)
                returns = await self.store_returns(wire, result)
                return {"returns": returns}
            method = getattr(self.actor_instance, wire["actor_method"])
            t_fetch = time.time()
            args, kwargs = await self.load_args(wire)
            if "trace_ctx" in wire:
                tracing.record_span(
                    "task.arg_fetch",
                    "arg_fetch",
                    t_fetch,
                    time.time() - t_fetch,
                    ctx=tracing.ctx_from_wire(wire),
                    task_id=wire["task_id"],
                )
            loop = asyncio.get_running_loop()

            with tracing.execute_scope(self.core, wire):
                tctx = tracing.current_context()
                if asyncio.iscoroutinefunction(method):
                    result = await method(*args, **kwargs)
                else:
                    def _run_with_ctx():
                        tok = tracing.set_context(tctx)
                        try:
                            return method(*args, **kwargs)
                        finally:
                            tracing.reset_context(tok)

                    result = await loop.run_in_executor(pool, _run_with_ctx)
                if (
                    wire["num_returns"] == -1
                    and conn is not None
                    and (inspect.isgenerator(result) or inspect.isasyncgen(result))
                ):
                    # Streaming actor generator: items are stored and
                    # reported to the owner AS PRODUCED (GeneratorItem
                    # pushes), so the consumer's iteration overlaps this
                    # producer. Runs INSIDE the trace scope — the generator
                    # body executes during this drain, and its nested
                    # submits must inherit the trace context.
                    idx = 0
                    if inspect.isasyncgen(result):
                        async def _advance():
                            try:
                                return True, await result.__anext__()
                            except StopAsyncIteration:
                                return False, None
                        advance = _advance
                    else:
                        def _advance_sync():
                            tok = tracing.set_context(tctx)
                            try:
                                return True, next(result)
                            except StopIteration:
                                return False, None
                            finally:
                                tracing.reset_context(tok)

                        async def _advance():
                            return await loop.run_in_executor(
                                pool, _advance_sync
                            )
                        advance = _advance
                    inflight = []
                    while True:
                        ok, item = await advance()
                        if not ok:
                            break
                        ret = await self.store_returns(
                            {"num_returns": 1,
                             "return_ids": [self._dyn_oid(wire, idx)]},
                            item,
                        )
                        # Acked delivery with a bounded window: a slow
                        # consumer throttles the producer instead of the
                        # owner buffering the whole stream (reference:
                        # _generator_backpressure_num_objects).
                        inflight.append(rpc.spawn(
                            self._send_generator_item(
                                conn, wire["task_id"], idx, ret[0]
                            )
                        ))
                        if len(inflight) >= _GEN_BACKPRESSURE_WINDOW:
                            await asyncio.gather(*inflight)
                            inflight = []
                        idx += 1
                    if inflight:
                        await asyncio.gather(*inflight)
                    return {"dynamic_count": idx}
            returns = await self.store_returns(wire, result)
            return {"returns": returns}
        except asyncio.CancelledError:
            # Same contract as the plain-task path above: ray.cancel must
            # cross the wire as typed TaskCancelledError, not as an opaque
            # CancelledError string the caller cannot dispatch on.
            from ray_tpu._private.common import TaskCancelledError

            return {"error": self._error_payload(TaskCancelledError("task cancelled"))}
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, SystemExit):
                asyncio.get_running_loop().call_later(0.1, os._exit, 0)
                return {"error": self._error_payload(RuntimeError("actor exited"))}
            logger.info("actor method %s raised: %r", wire.get("actor_method"), e)
            return {"error": self._error_payload(e)}

    async def _send_generator_item(self, conn, task_id: str, idx: int, ret: dict):
        """One acked GeneratorItem delivery (the ack is the flow-control
        token — a window of these bounds producer run-ahead)."""
        return await conn.call(
            "GeneratorItem", {"task_id": task_id, "index": idx, "ret": ret}
        )

    async def handle_exit(self, conn, p):
        # Final deadline-stats flush: overruns observed in this worker's last
        # report interval must reach the GCS aggregate before the process
        # dies, or the no-call-outlives-deadline invariant goes blind to
        # them. Bounded so a dead GCS cannot stall the exit.
        delta = _deadline_stats_delta(self.core.worker_id)
        if delta is not None:
            try:
                await asyncio.wait_for(
                    self.core.gcs.call("ReportDeadlineStats", delta), timeout=1.0
                )
            except Exception:
                pass
        # Same for the runtime-telemetry registry: counters recorded since
        # the last periodic flush (and any undrained flight events) ride one
        # bounded final report instead of dying with the process.
        tel = telemetry.flush_delta(self.core.worker_id, self.core.node_id)
        if tel is not None:
            try:
                await asyncio.wait_for(
                    self.core.gcs.call("ReportTelemetry", tel), timeout=1.0
                )
            except Exception:
                telemetry.restore_delta(tel)
        # And the trace plane: buffered task-event spans plus runtime spans
        # must outlive the worker (flush-on-exit span delivery) — a span
        # recorded milliseconds before exit is exactly the one a trace of a
        # short task needs.
        if tracing.enabled():
            try:
                await asyncio.wait_for(self.core._flush_task_events(), timeout=1.0)
            except Exception:
                pass
            try:
                await asyncio.wait_for(
                    tracing.flush_spans_once(
                        self.core.gcs.call,
                        self.core.worker_id,
                        self.core.node_id,
                    ),
                    timeout=1.0,
                )
            except Exception:
                pass
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"ok": True}


async def amain() -> None:
    raylet_addr = (
        os.environ["RAY_TPU_RAYLET_HOST"],
        int(os.environ["RAY_TPU_RAYLET_PORT"]),
    )
    gcs_addr = (os.environ["RAY_TPU_GCS_HOST"], int(os.environ["RAY_TPU_GCS_PORT"]))
    gcs_leader_file = os.environ.get("RAY_TPU_GCS_LEADER_FILE") or None
    if gcs_leader_file:
        # HA mode: the env address is whatever leader the raylet knew at
        # spawn time — a worker booting mid/post-failover must dial the
        # CURRENT leader from the pointer file instead.
        from ray_tpu._private import gcs_ha

        gcs_addr = gcs_ha.resolve_leader_file(gcs_leader_file) or gcs_addr
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    node_id = os.environ["RAY_TPU_NODE_ID"]
    session = os.environ["RAY_TPU_SESSION"]

    server = rpc.Server("127.0.0.1", 0)
    addr = await server.start()

    raylet_conn = await rpc.connect(
        *raylet_addr, handlers=server._handlers, sync_handlers=server._sync_handlers
    )
    gcs_conn = await rpc.connect(
        *gcs_addr, handlers=server._handlers, sync_handlers=server._sync_handlers
    )

    core = CoreWorker(
        job_id=os.environ.get("RAY_TPU_JOB_ID", ""),
        session_name=session,
        node_id=node_id,
        gcs_conn=gcs_conn,
        raylet_conn=raylet_conn,
        is_driver=False,
        worker_id=worker_id,
        server=server,
        gcs_leader_file=gcs_leader_file,
    )
    core.addr = addr
    core.raylet_addr = raylet_addr
    core.start_background()

    executor = Executor(core)

    # Install the sync-facing global worker so user code can call
    # ray_tpu.get()/put() from inside tasks.
    from ray_tpu._private import worker as worker_mod

    worker_mod.attach_existing(core, asyncio.get_running_loop())

    # Native direct-call channel (reference: the worker-side PushTask fast
    # lane of the C++ core worker). Optional: without the extension the RPC
    # path serves everything.
    fp_port = None
    fp_server_id = None
    if config.fastpath_enabled:
        try:
            from ray_tpu._native import _fastpath as _fp

            executor._fp_loop = asyncio.get_running_loop()
            fp_server_id, fp_port = _fp.serve(
                "127.0.0.1", 0, executor.fastpath_exec
            )
        except Exception:
            fp_port = None

    reply = await raylet_conn.call(
        "RegisterWorker",
        {"worker_id": worker_id, "addr": list(addr), "fp_port": fp_port},
    )
    core.job_id = core.job_id or reply.get("job_id", "")

    async def _deadline_report_loop() -> None:
        """Flush deadline-enforcement deltas to the GCS aggregate so overruns
        inside worker subprocesses are visible to the cluster-wide
        no-call-outlives-deadline invariant, not just driver-local stats."""
        interval = config.rpc_deadline_report_interval_s
        if interval <= 0:
            return
        while True:
            await asyncio.sleep(interval)
            delta = _deadline_stats_delta(worker_id)
            if delta is None:
                continue
            try:
                await core.gcs.call("ReportDeadlineStats", delta)
            except Exception:
                _restore_deadline_delta(delta)

    rpc.spawn(_deadline_report_loop())

    # Exit if the raylet link dies: an unmanaged worker must not linger.
    while not raylet_conn.closed:
        await asyncio.sleep(0.5)
    os._exit(0)


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {os.environ.get('RAY_TPU_WORKER_ID', '?')[:8]}] %(message)s",
    )
    rpc.install_event_loop()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
