"""GCS: the cluster control service (control plane singleton).

TPU-native analog of the reference's gcs_server (src/ray/gcs/gcs_server/gcs_server.h:219):
one asyncio process holding cluster state — node membership, actor FSM with
restarts, placement-group 2PC, internal KV (which doubles as the function
table), pubsub, and the job table. Persistence is pluggable (reference:
store_client.h:33): in-memory by default, sqlite write-through for GCS fault
tolerance — kill and restart the GCS and raylets/workers reconnect,
re-register, and detached actors survive (analog of the Redis-backed FT mode
+ NotifyGCSRestart reconnect protocol, node_manager.proto:373).

Health checking follows the reference's connection+liveness model
(gcs_health_check_manager.cc): raylets hold a persistent RPC connection and
push periodic resource updates; a dropped connection or missed deadline marks
the node dead, which drives actor restarts and PG rescheduling.
"""

from __future__ import annotations

import asyncio
import bisect
import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import msgpack

from collections import deque

from ray_tpu._private import aiocheck, rpc, telemetry, wire
from ray_tpu._private.pubsub import Publisher
from ray_tpu._private.common import PlacementGroupSpec, ResourceSet, config

logger = logging.getLogger(__name__)

# Subscriber-side gap detection (GcsClient): counted in the raylet/driver
# process that noticed the gap and flushed with its telemetry.
_TEL_SUB_GAP = telemetry.counter(
    "gcs_client",
    "pubsub_gap_snapshots",
    "pubsub seq gaps detected by a subscriber (each pulls a snapshot)",
)

# Actor FSM states (reference: gcs_actor_manager.cc). The legal transitions
# are declared machine-readably in ray_tpu/devtools/protocols.py and every
# assignment is checked against them at lint time.
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"

# Node FSM states (reference: gcs_node_manager.cc). Same wire strings as the
# actor ALIVE/DEAD, but a separate two-state machine — keep distinct names so
# the protocol checker can tell the machines apart.
NODE_ALIVE = "ALIVE"
NODE_DEAD = "DEAD"

# Placement-group FSM states (reference: gcs_placement_group_mgr.cc).
PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_RESCHEDULING = "RESCHEDULING"
PG_REMOVED = "REMOVED"
PG_INFEASIBLE = "INFEASIBLE"


class NodeInfo:
    def __init__(self, node_id: str, addr, resources: Dict[str, int], labels, conn):
        self.node_id = node_id
        self.addr = tuple(addr)
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = labels or {}
        self.conn: rpc.Connection = conn
        self.state = NODE_ALIVE
        self.last_seen = time.monotonic()
        # Health-check manager state (reference: gcs_health_check_manager.cc).
        self.health_misses = 0
        self.health_probe_inflight = False
        # Last resource-report version accepted from this raylet (syncer
        # staleness guard, reference: ray_syncer.h versioned messages).
        self.report_version = -1

    def to_wire(self, include_conn=False) -> dict:
        return {
            "node_id": self.node_id,
            "addr": list(self.addr),
            "total": self.total,
            "available": self.available,
            "labels": self.labels,
            "state": self.state,
        }


class ActorInfo:
    def __init__(self, actor_id: str, spec: dict):
        self.actor_id = actor_id
        self.spec = spec  # actor-creation TaskSpec wire dict
        self.state = PENDING_CREATION
        self.addr: Optional[Tuple[str, int]] = None
        self.worker_id: Optional[str] = None
        self.node_id: Optional[str] = None
        self.num_restarts = 0
        self.max_restarts = spec.get("max_restarts", 0)
        self.name = spec.get("actor_name")
        self.namespace = spec.get("namespace") or "default"
        self.job_id = spec.get("job_id")
        self.detached = (spec.get("scheduling_strategy") or {}).get("detached", False)
        self.death_cause: Optional[str] = None
        self.pending: List[asyncio.Future] = []

    def to_wire(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "addr": list(self.addr) if self.addr else None,
            "worker_id": self.worker_id,
            "node_id": self.node_id,
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "name": self.name,
            "namespace": self.namespace,
            "job_id": self.job_id,
            "death_cause": self.death_cause,
            "class_name": self.spec.get("name"),
            "max_task_retries": self.spec.get("max_task_retries", 0),
        }


class PlacementGroupInfo:
    def __init__(self, spec: PlacementGroupSpec):
        self.spec = spec
        self.state = PG_PENDING
        self.bundle_nodes: List[Optional[str]] = [None] * len(spec.bundles)
        self.pending: List[asyncio.Future] = []


class GcsServer:
    """The control service. Start with `await GcsServer(...).start()`."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        session_name: str = "",
        persist_path: Optional[str] = None,
        persist_backend: Optional[str] = None,
        term: Optional[int] = None,
    ):
        from ray_tpu._private.gcs_store import ReplicatedStoreClient, make_store

        self.server = rpc.Server(host, port)
        self.session_name = session_name
        # Shared single-loop state: every handler below may touch these
        # across awaits. aiocheck.track is a no-op unless RAY_TPU_AIOCHECK=1,
        # in which case mutations are attributed to their asyncio task so
        # cross-task interleaving hazards surface at runtime.
        self.nodes: Dict[str, NodeInfo] = aiocheck.track("gcs.nodes")
        self.actors: Dict[str, ActorInfo] = aiocheck.track("gcs.actors")
        # (ns, name) -> actor_id
        self.named_actors: Dict[Tuple[str, str], str] = aiocheck.track(
            "gcs.named_actors"
        )
        self.kv: Dict[Tuple[str, str], bytes] = aiocheck.track("gcs.kv")
        # Bounded per-subscriber pubsub (reference: pubsub/publisher.h).
        self.publisher = Publisher()
        self.jobs: Dict[str, dict] = aiocheck.track("gcs.jobs")
        self.placement_groups: Dict[str, PlacementGroupInfo] = aiocheck.track(
            "gcs.placement_groups"
        )
        self.task_events: List[dict] = []  # ring buffer of task state events
        # Trace-span ring: submit/execute spans diverted from AddTaskEvents
        # plus runtime-internal spans delivered via ReportSpans, one store
        # for list_spans()/timeline()/critical_path().
        self.spans: List[dict] = []
        # Cluster-wide deadline-enforcement aggregate, fed by worker
        # subprocess flushes (ReportDeadlineStats deltas + exit-time flush).
        # The chaos no-call-outlives-deadline invariant reads `overruns`
        # here so worker-side overruns are visible, not just driver-side.
        self.worker_deadline_stats: Dict[str, Any] = {  # telemetry: allow-adhoc-stats
            "met": 0,
            "shed": 0,
            "enforced": 0,
            "overruns": [],  # (worker_id, method, seconds late)
        }
        # Cluster-wide runtime-telemetry aggregate keyed by
        # (component, node, name), fed by per-process ReportTelemetry
        # flushes (telemetry.py); the dashboard /metrics endpoint renders
        # it as Prometheus text next to the app-metric export.
        self.telemetry: Dict[str, Any] = telemetry.new_aggregate()
        # Merged flight-recorder ring: lifecycle events drained from every
        # reporting process, kept in arrival order (entries carry wall-clock
        # timestamps; the dump step sorts). Sized for a whole cluster.
        self.flight_events: deque = deque(
            maxlen=8 * config.telemetry_flight_capacity
        )
        # Service-latency histogram observed around every async handler
        # dispatch on this server (rpc.Connection dispatch_observer).
        lat = telemetry.histogram(
            "gcs",
            "rpc_latency_s",
            "GCS handler service latency by method",
            buckets=telemetry.LATENCY_BUCKETS_S,
        )
        _lat_cells: Dict[str, Any] = {}

        def _observe_latency(method: str, dt: float) -> None:
            cell = _lat_cells.get(method)
            if cell is None:
                cell = _lat_cells[method] = lat.cell(method=method)
            cell.observe(dt)

        self.server.dispatch_observer = _observe_latency
        # Monotonic cluster-view version; every membership/resource change
        # bumps it and broadcasts the scheduling head (reference:
        # ray_syncer.h:88 versioned sync streams). The GCS is the one place
        # that sees every resource report, so IT maintains the
        # utilization-sorted order incrementally (O(log n) bisect per
        # report) and subscribers receive only the sorted head — the
        # least-utilized candidate set every top-k/spillback pick needs.
        # Broadcasting full per-node deltas instead would cost every
        # subscriber O(dirty) decode+apply per flush, which measured
        # O(N^2) cluster-wide during lease storms.
        self.view_version = 0
        # Membership/total-capacity epoch: keys subscriber-side caches that
        # only depend on cluster shape (e.g. the SPREAD ring).
        self.view_epoch = 0
        self._util_sorted: List[Tuple[float, str]] = []  # (util, node_id)
        self._node_utils: Dict[str, float] = {}
        # Head batching (scheduler_view_batch_ms): mutations coalesce for
        # one window and flush as a single versioned head broadcast, so a
        # grant storm at N nodes costs subscribers/window broadcasts
        # instead of subscribers*grants.
        self._view_dirty = False
        self._view_flush_handle: Optional[asyncio.TimerHandle] = None
        # Structured events (reference: src/ray/util/event.cc): durable
        # JSONL + queryable ring, served via ListEvents.
        from ray_tpu._private.events import EventLogger

        self.events = EventLogger(session_name or "default", "GCS")
        self._pending_actor_queue: List[str] = []
        self._wake_scheduler = asyncio.Event()
        self._scheduler_task: Optional[asyncio.Task] = None
        self._bg_tasks: List[asyncio.Task] = []
        # True while stop() tears the server down. Connection drops during a
        # deliberate shutdown are us leaving, not peers dying — reacting to
        # them would persist bogus node-death state (actors marked
        # RESTARTING/DEAD) that a restarted GCS then faithfully reloads.
        self._stopping = False
        # Actors reloaded as ALIVE whose hosting raylet has not yet
        # re-registered and confirmed them (RegisterNode "actors" report).
        # Whatever remains when the reconcile sweep runs gets probed.
        self._restored_unconfirmed: Set[str] = set()
        # Persistence (reference: StoreClient, store_client.h:33). The live
        # state above stays the source of truth; mutations write through to
        # the store, and a restarted GCS reloads it (GCS fault tolerance).
        #
        # HA (gcs_persist_backend=replicated, docs/fault_tolerance.md §HA):
        # the store ships every write to follower logs and carries a
        # leadership term. ``term`` is set by a promoting standby; a fresh
        # start (or restart-in-place) re-asserts leadership at
        # recovered_term + 1 — every leadership is a new term, so a
        # survivor of the old one is fenced the moment we open the store.
        self.leader_term = 0
        self.fenced = False
        self._persist_path = persist_path
        self.store = make_store(
            persist_path,
            backend=persist_backend,
            term=term,
            on_fenced=self._on_store_fenced,
        )
        # Cross-process standbys subscribed to the quorum-acked commit
        # stream (ShipSubscribe); each push mirrors the raw WAL frames of
        # one group commit (gcs_ha.GcsStandby rpc mode).
        self._ship_subs: set = set()
        if isinstance(self.store, ReplicatedStoreClient):
            if term is None:
                self.store.set_term(self.store.term + 1)
            self.leader_term = self.store.term
            self.store.ship_listener = self._on_ship_commit
        self._load_from_store()
        self._register_handlers()

    def _spawn(self, coro) -> asyncio.Task:
        task = rpc.spawn(coro)
        self._bg_tasks.append(task)
        self._bg_tasks = [t for t in self._bg_tasks if not t.done()]
        return task

    def _spawn_pg_schedule(self, pg: "PlacementGroupInfo") -> asyncio.Task:
        """Supervised ``_schedule_pg`` spawn: a crashed scheduling task must
        not strand the PG in PENDING/RESCHEDULING with ``pg.pending``
        futures nobody will ever resolve (CreatePlacementGroup callers with
        ``wait_ready`` park on those)."""
        task = self._spawn(self._schedule_pg(pg))

        def _done(t: asyncio.Task, pg=pg) -> None:
            if t.cancelled() or t.exception() is None:
                return
            exc = t.exception()
            logger.error(
                "placement group %s scheduling crashed: %s",
                pg.spec.pg_id[:8],
                exc,
            )
            if pg.state in (PG_PENDING, PG_RESCHEDULING):
                pg.state = PG_INFEASIBLE
                self._persist_pg(pg)
            for fut in pg.pending:
                if not fut.done():
                    fut.set_exception(
                        rpc.RpcError(
                            f"placement group {pg.spec.pg_id[:8]} "
                            f"scheduling failed: {exc}"
                        )
                    )
            pg.pending.clear()

        task.add_done_callback(_done)
        return task

    # -- persistence (reference: gcs_table_storage.cc write-through) ---------

    def _persist_actor(self, actor: ActorInfo) -> None:
        rec = actor.to_wire()
        rec["spec"] = actor.spec
        self.store.put("actors", actor.actor_id, msgpack.packb(rec, use_bin_type=True))

    def _persist_named(self) -> None:
        rec = {f"{ns}\x00{name}": aid for (ns, name), aid in self.named_actors.items()}
        self.store.put("named", "all", msgpack.packb(rec, use_bin_type=True))

    def _persist_kv(self, ns: str, key: str, value: Optional[bytes]) -> None:
        skey = f"{ns}\x00{key}"
        if value is None:
            self.store.delete("kv", skey)
        else:
            self.store.put("kv", skey, value)

    def _persist_job(self, job_id: str) -> None:
        self.store.put(
            "jobs", job_id, msgpack.packb(self.jobs[job_id], use_bin_type=True)
        )

    def _persist_pg(self, pg: PlacementGroupInfo) -> None:
        rec = {
            "spec": pg.spec.to_wire(),
            "state": pg.state,
            "bundle_nodes": pg.bundle_nodes,
        }
        self.store.put("pgs", pg.spec.pg_id, msgpack.packb(rec, use_bin_type=True))

    def _load_from_store(self) -> None:
        """Reload control-plane state after a GCS restart. Node membership is
        not persisted — raylets re-register over their reconnect loop."""
        for skey, value in self.store.get_all("kv").items():
            ns, _, key = skey.partition("\x00")
            self.kv[(ns, key)] = value
        for job_id, blob in self.store.get_all("jobs").items():
            self.jobs[job_id] = msgpack.unpackb(blob, raw=False)
        named = self.store.get_all("named").get("all")
        if named:
            for skey, aid in msgpack.unpackb(named, raw=False).items():
                ns, _, name = skey.partition("\x00")
                self.named_actors[(ns, name)] = aid
        for actor_id, blob in self.store.get_all("actors").items():
            rec = msgpack.unpackb(blob, raw=False)
            actor = ActorInfo(actor_id, rec["spec"])
            # Restart restore: the persisted state was validated as a legal
            # FSM state when it was written, not re-derivable statically.
            actor.state = rec["state"]  # protocol: disable=protocol-unresolvable
            actor.addr = tuple(rec["addr"]) if rec.get("addr") else None
            actor.worker_id = rec.get("worker_id")
            actor.node_id = rec.get("node_id")
            actor.num_restarts = rec.get("num_restarts", 0)
            actor.max_restarts = rec.get("max_restarts", 0)
            actor.death_cause = rec.get("death_cause")
            self.actors[actor_id] = actor
            if actor.state in (PENDING_CREATION, RESTARTING):
                # Reconciliation: the creation was in flight when the GCS
                # died. Any lease it held lives (or died) with its raylet,
                # which will cancel/re-grant on re-registration — re-drive
                # the placement from a clean slate rather than trusting a
                # half-recorded grant.
                actor.addr = None
                actor.worker_id = None
                actor.node_id = None
                self._pending_actor_queue.append(actor_id)
            elif actor.state == ALIVE:
                self._restored_unconfirmed.add(actor_id)
        for pg_id, blob in self.store.get_all("pgs").items():
            rec = msgpack.unpackb(blob, raw=False)
            pg = PlacementGroupInfo(PlacementGroupSpec.from_wire(rec["spec"]))
            # Restart restore (see actor restore above).
            pg.state = rec["state"]  # protocol: disable=protocol-unresolvable
            pg.bundle_nodes = rec.get("bundle_nodes") or pg.bundle_nodes
            self.placement_groups[pg_id] = pg
        if self._pending_actor_queue:
            self._wake_scheduler.set()

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> Tuple[str, int]:
        addr = await self.server.start()
        self.server.on_disconnect(self._on_disconnect)
        self._scheduler_task = rpc.spawn(self._actor_scheduler_loop())
        if config.health_check_period_s > 0:
            self._spawn(self._health_check_loop())
        # Resume work interrupted by a restart: unplaced PGs re-enter the
        # scheduling loop, and actors recorded ALIVE are reconciled against
        # the nodes that actually re-register.
        for pg in self.placement_groups.values():
            if pg.state in (PG_PENDING, PG_RESCHEDULING):
                self._spawn(self._schedule_pg(pg))
        if any(a.state == ALIVE for a in self.actors.values()):
            self._spawn(self._reconcile_restored_actors())
        if any(g.state == PG_CREATED for g in self.placement_groups.values()):
            self._spawn(self._reconcile_restored_pgs())
        if self.leader_term:
            # HA: assert leadership (record + pointer file) before serving
            # traffic, then keep the lease renewed from a background loop.
            from ray_tpu._private import gcs_ha

            gcs_ha.write_leadership(self.store, self.leader_term, addr)
            gcs_ha.write_leader_file(
                gcs_ha.leader_file_path(self._persist_path), *addr
            )
            gcs_ha.note_role(leader=True)
            self._spawn(self._leader_lease_loop(addr))
        logger.info("gcs listening on %s:%s", *addr)
        return addr

    async def _leader_lease_loop(self, addr) -> None:
        """Re-assert the leadership record (term + deadline) every third of
        the lease. A write rejected by the store's fence means a standby
        promoted past us — ``_on_store_fenced`` demotes; this loop just
        stops renewing."""
        from ray_tpu._private import gcs_ha
        from ray_tpu._private.rpc import StaleLeaderError

        while not self._stopping and not self.fenced:
            await asyncio.sleep(config.gcs_leader_lease_s / 3.0)
            if self._stopping or self.fenced:
                return
            try:
                gcs_ha.write_leadership(self.store, self.leader_term, addr)
            except StaleLeaderError:
                return  # the store's on_fenced callback owns the demotion

    def _on_store_fenced(self) -> None:
        """Store callback: a write from our term bounced off a newer fence.
        We are no longer the leader — stop serving cleanly (reads included:
        a fenced GCS's view diverges from the real one immediately)."""
        if self.fenced or self._stopping:
            self.fenced = True
            return
        self.fenced = True
        logger.warning(
            "gcs leadership term %d fenced by a newer leader: demoting",
            self.leader_term,
        )
        from ray_tpu._private import gcs_ha

        gcs_ha.note_role(leader=False)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        # Short drain window before tearing the server down: the write that
        # discovered the fence is mid-dispatch, and its typed
        # StaleLeaderError reply must reach the caller before the transport
        # closes. The store is already fenced, so nothing can be acked in
        # the window — only rejections and stale reads escape.
        loop.call_later(0.1, lambda: rpc.spawn(self.stop()))

    async def _health_check_loop(self) -> None:
        """Active node health probing (reference: gcs_health_check_manager.cc
        + knobs ray_config_def.h:847-853). Connection loss already triggers
        death handling; this catches the wedged-but-connected raylet — a
        stuck event loop that keeps its TCP session alive while serving
        nothing. Each node is Pinged every period; `health_check_failure_
        threshold` consecutive timeouts/errors mark it DEAD."""
        await asyncio.sleep(config.health_check_initial_delay_s)
        while True:
            await asyncio.sleep(config.health_check_period_s)
            for node in list(self.nodes.values()):
                if node.state != NODE_ALIVE or node.health_probe_inflight:
                    continue
                node.health_probe_inflight = True
                rpc.spawn(self._probe_node(node))

    async def _probe_node(self, node: NodeInfo) -> None:
        try:
            await node.conn.call(
                "Ping", {}, timeout=config.health_check_timeout_s
            )
            node.health_misses = 0
            node.last_seen = time.monotonic()
        except (rpc.RpcError, asyncio.TimeoutError, OSError):
            node.health_misses += 1
            logger.warning(
                "health check miss %d/%d for node %s",
                node.health_misses,
                config.health_check_failure_threshold,
                node.node_id[:8],
            )
            if (
                node.health_misses >= config.health_check_failure_threshold
                and node.state == NODE_ALIVE
            ):
                logger.error(
                    "node %s failed %d consecutive health checks: marking DEAD",
                    node.node_id[:8],
                    node.health_misses,
                )
                await self._handle_node_death(node.node_id)
                # Drop the (still-open) link: an unwedged raylet must learn
                # it was declared dead — its client reconnects and
                # re-registers as a fresh node rather than running zombie
                # actors against a DEAD entry forever.
                try:
                    await node.conn.close()
                except Exception:
                    pass
        finally:
            node.health_probe_inflight = False

    async def _reconcile_restored_actors(self) -> None:
        """Post-restart sweep: an actor restored as ALIVE whose node never
        re-registered (or whose worker died during the outage) is treated as
        a worker death, driving the normal restart/fail FSM. Actors already
        confirmed by their raylet's re-registration report (the "actors"
        field on RegisterNode) are skipped — at hundreds of nodes the
        confirmations shrink the probe storm to just the genuinely
        uncertain residue."""
        await asyncio.sleep(config.health_check_initial_delay_s)
        unconfirmed, self._restored_unconfirmed = (
            self._restored_unconfirmed,
            set(),
        )
        for actor_id in unconfirmed:
            actor = self.actors.get(actor_id)
            if actor is None or actor.state != ALIVE:
                continue
            node = self.nodes.get(actor.node_id) if actor.node_id else None
            dead = node is None or node.state != NODE_ALIVE
            if not dead and actor.addr:
                try:
                    conn = node.conn
                    reply = await conn.call(
                        "KillWorker",
                        {"worker_id": actor.worker_id, "probe": True},
                        timeout=config.rpc_control_timeout_s,
                    )
                    dead = not reply.get("alive", False)
                except rpc.RpcError:
                    dead = True
            if dead:
                await self._on_actor_worker_death(
                    actor, "node or worker lost while GCS was down"
                )

    async def _reconcile_restored_pgs(self) -> None:
        """The PG analog: a group restored as CREATED whose bundle nodes
        never re-registered lost those reservations with the raylet — the
        same CREATED -> RESCHEDULING transition a node death drives, then
        the normal 2PC re-placement."""
        await asyncio.sleep(config.health_check_initial_delay_s)
        for pg in list(self.placement_groups.values()):
            if pg.state != PG_CREATED:
                continue
            lost = any(
                nid
                and (
                    nid not in self.nodes
                    or self.nodes[nid].state != NODE_ALIVE
                )
                for nid in pg.bundle_nodes
            )
            if lost:
                pg.state = PG_RESCHEDULING
                self._persist_pg(pg)
                self._spawn(self._schedule_pg(pg))

    async def stop(self) -> None:
        self._stopping = True
        if self._view_flush_handle is not None:
            self._view_flush_handle.cancel()
            self._view_flush_handle = None
        if self._scheduler_task:
            self._scheduler_task.cancel()
        for t in self._bg_tasks:
            t.cancel()
        await self.server.stop()
        # Graceful shutdown owns the store handle: close() checkpoints the
        # sqlite WAL / flushes+fsyncs the group-commit tail.
        self.store.close()

    async def crash(self) -> None:
        """Abrupt death (kill -9 analog, driven by the chaos ``crash_gcs``
        nemesis): transports drop and the store sees ``crash()`` instead of
        ``close()`` — no WAL checkpoint, no compaction, no final fsync —
        so the on-disk state is exactly what a killed process leaves, and
        recovery (torn-tail truncation + the reconcile sweeps) has to earn
        the restart."""
        self._stopping = True
        if self._view_flush_handle is not None:
            self._view_flush_handle.cancel()
            self._view_flush_handle = None
        if self._scheduler_task:
            self._scheduler_task.cancel()
        for t in self._bg_tasks:
            t.cancel()
        await self.server.stop()
        self.store.crash()

    def _register_handlers(self) -> None:
        s = self.server
        s.register("RegisterNode", self._register_node)
        s.register("UnregisterNode", self._unregister_node)
        s.register("ListEvents", self._list_events)
        s.register("GetAllNodes", self._get_all_nodes)
        s.register("UpdateResources", self._update_resources)
        s.register_sync("UpdateResources", self._update_resources_sync)
        s.register("CreateActor", self._create_actor)
        s.register("GetActor", self._get_actor)
        s.register("GetNamedActor", self._get_named_actor)
        s.register("ListActors", self._list_actors)
        s.register("ListNamedActors", self._list_named_actors)
        s.register("ReportActorReady", self._report_actor_ready)
        s.register("ReportWorkerDied", self._report_worker_died)
        s.register("ReportDeadlineStats", self._report_deadline_stats)
        s.register("ReportTelemetry", self._report_telemetry)
        s.register("GetTelemetry", self._get_telemetry)
        s.register("KillActor", self._kill_actor)
        s.register("KVPut", self._kv_put)
        s.register("KVGet", self._kv_get)
        s.register("KVDel", self._kv_del)
        s.register("KVKeys", self._kv_keys)
        s.register("KVExists", self._kv_exists)
        s.register("Subscribe", self._subscribe)
        s.register("Unsubscribe", self._unsubscribe)
        s.register("Publish", self._publish)
        s.register("Snapshot", self._snapshot)
        s.register("RegisterJob", self._register_job)
        s.register("JobFinished", self._job_finished)
        s.register("ListJobs", self._list_jobs)
        s.register("CreatePlacementGroup", self._create_pg)
        s.register("WaitPlacementGroupReady", self._wait_pg_ready)
        s.register("RemovePlacementGroup", self._remove_pg)
        s.register("GetPlacementGroup", self._get_pg)
        s.register("ListPlacementGroups", self._list_pgs)
        s.register("AddTaskEvents", self._add_task_events)
        s.register("ListTaskEvents", self._list_task_events)
        s.register("ReportSpans", self._report_spans)
        s.register("ListSpans", self._list_spans)
        s.register("GetClusterStatus", self._cluster_status)
        s.register("Ping", self._ping)
        s.register("ShipSubscribe", self._ship_subscribe)
        s.register("ShipSnapshot", self._ship_snapshot)

    # -- HA replication stream (cross-process standby feed) ------------------

    async def _ship_subscribe(self, conn: rpc.Connection, p: dict) -> dict:
        """Subscribe a cross-process standby to the quorum-acked commit
        stream; every subsequent group commit is pushed as one ShipFrames
        frame. The reply's watermark tells the standby where the pushes
        start — it bootstraps the gap before it with ShipSnapshot."""
        from ray_tpu._private.gcs_store import ReplicatedStoreClient

        if not isinstance(self.store, ReplicatedStoreClient):
            return {"ok": False, "term": 0, "seq": 0}
        self._ship_subs.add(conn)
        return {"ok": True, "term": self.store.term, "seq": self.store.seq}

    async def _ship_snapshot(self, conn: rpc.Connection, p: dict) -> dict:
        from ray_tpu._private.gcs_store import ReplicatedStoreClient

        if not isinstance(self.store, ReplicatedStoreClient):
            return {"ok": False, "term": 0, "seq": 0, "snap": b""}
        snap, term, seq = self.store.snapshot_tables()
        return {"ok": True, "term": term, "seq": seq, "snap": snap}

    def _on_ship_commit(self, frames: bytes, term: int, seq: int, prev_seq: int) -> None:
        """store.ship_listener: fan one quorum-acked group commit out to
        subscribed standbys. Runs on the GCS loop (the flush is scheduled
        with call_soon), so push_nowait is safe; a dead subscriber is
        dropped by the disconnect callback."""
        if not self._ship_subs:
            return
        payload = {"frames": frames, "term": term, "seq": seq, "prev_seq": prev_seq}
        for conn in list(self._ship_subs):
            try:
                conn.push_nowait("ShipFrames", payload)
            except rpc.ConnectionLost:
                self._ship_subs.discard(conn)

    # -- nodes --------------------------------------------------------------

    @staticmethod
    def _util_of(total: Dict[str, int], available: Dict[str, int]) -> float:
        util = 0.0
        for k, tot in total.items():
            if tot > 0 and not k.startswith("node:"):
                util = max(util, 1.0 - available.get(k, 0) / tot)
        return util

    def _bump_view(self, node: "NodeInfo", membership: bool = False) -> None:
        """One cluster-view mutation: refresh the node's slot in the
        utilization-sorted index (O(log n)), then broadcast the scheduling
        head so every raylet's candidate set converges without polling.
        ``membership=True`` (join/death/total change) also bumps the shape
        epoch that invalidates subscriber-side rings. With
        scheduler_view_batch_ms > 0 the broadcast is coalesced into the
        next flush window instead of published immediately."""
        nid = node.node_id
        old = self._node_utils.pop(nid, None)
        if old is not None:
            i = bisect.bisect_left(self._util_sorted, (old, nid))
            if i < len(self._util_sorted) and self._util_sorted[i] == (old, nid):
                del self._util_sorted[i]
        if node.state == NODE_ALIVE:
            util = self._util_of(node.total, node.available)
            bisect.insort(self._util_sorted, (util, nid))
            self._node_utils[nid] = util
        if membership:
            # Monotonic broadcast version: a retried RegisterNode bumping it
            # twice only costs one extra (idempotent) view broadcast —
            # subscribers key on "newest epoch wins", gaps are meaningless.
            self.view_epoch += 1  # exc-flow: disable=retry-unsafe-mutation
        batch_ms = config.scheduler_view_batch_ms
        if batch_ms <= 0:
            self._publish_view_head()
            return
        self._view_dirty = True
        if self._view_flush_handle is None:
            self._view_flush_handle = asyncio.get_running_loop().call_later(
                batch_ms / 1000.0, self._flush_view_head
            )

    def _flush_view_head(self) -> None:
        self._view_flush_handle = None
        if not self._view_dirty or self._stopping:
            return
        self._view_dirty = False
        self._publish_view_head()

    # The head is capped: a pick only ever samples among the least-utilized
    # candidates, and past a few dozen the marginal spread quality is nil
    # while broadcast decode cost at N subscribers is linear in head size.
    _VIEW_HEAD_CAP = 16

    def _publish_view_head(self) -> None:
        """Broadcast {"v", "epoch", "n", "head"}: the n alive-node count
        plus the ``head`` least-utilized nodes in utilization order —
        everything the hybrid top-k pick and spillback targeting consume,
        sized O(head cap) regardless of cluster size."""
        # Monotonic, gap-tolerant (see view_epoch above): double-bump on a
        # retried registration is benign.
        self.view_version += 1  # exc-flow: disable=retry-unsafe-mutation
        self._publish_msg("syncer:nodes", self._view_head_msg())

    def _view_head_msg(self) -> dict:
        head = []
        for util, nid in self._util_sorted:
            node = self.nodes.get(nid)
            if node is None or node.state != NODE_ALIVE:
                continue
            head.append(
                {
                    "node_id": nid,
                    "addr": list(node.addr),
                    "total": node.total,
                    "available": node.available,
                    "util": util,
                }
            )
            if len(head) >= self._VIEW_HEAD_CAP:
                break
        return {
            "v": self.view_version,
            "epoch": self.view_epoch,
            "n": len(self._util_sorted),
            "head": head,
        }

    async def _register_node(self, conn, p):
        info = NodeInfo(p["node_id"], p["addr"], p["resources"], p.get("labels"), conn)
        self.nodes[p["node_id"]] = info
        conn.context["node_id"] = p["node_id"]
        self.events.emit(
            "NODE_ADDED",
            f"node {p['node_id'][:8]} joined",
            node_id=p["node_id"],
            resources=p["resources"],
        )
        # Lease-picture rebuild after a GCS restart: the raylet reports the
        # actor workers it is hosting, confirming restored-ALIVE actors
        # without the reconcile sweep having to probe each one (reference:
        # NotifyGCSRestart — raylets own the ground truth about workers).
        for rec in p.get("actors") or []:
            actor = self.actors.get(rec.get("actor_id") or "")
            if (
                actor is not None
                and actor.state == ALIVE
                and actor.node_id == p["node_id"]
                and actor.worker_id == rec.get("worker_id")
            ):
                self._restored_unconfirmed.discard(actor.actor_id)
        self._publish_msg("nodes", {"event": "added", "node": info.to_wire()})
        self._bump_view(info, membership=True)
        self._wake_scheduler.set()
        return {"ok": True, "session_name": self.session_name}

    async def _list_events(self, conn, p):
        return {
            "events": self.events.list(
                severity=p.get("severity"),
                label=p.get("label"),
                limit=p.get("limit", 1000),
            )
        }

    async def _unregister_node(self, conn, p):
        """Graceful node departure (reference: DrainNode/UnregisterNode in
        gcs_node_manager.cc): same state transition as a detected death —
        actors on the node still fail over — but logged as a planned exit,
        not a health-check death."""
        await self._handle_node_death(p["node_id"], graceful=True)
        return {"ok": True}

    async def _get_all_nodes(self, conn, p):
        return {
            "nodes": [n.to_wire() for n in self.nodes.values()],
            "v": self.view_version,
            "epoch": self.view_epoch,
        }

    async def _update_resources(self, conn, p):
        return self._apply_update_resources(p)

    def _update_resources_sync(self, conn, msgid, p):
        """Inline fast path: resource reports are the highest-volume RPC the
        GCS serves (every grant/release on every raylet lands here) and the
        handler never awaits — dispatch it from data_received with no task.
        Raylets normally send reports as pushes (msgid None, no reply): the
        report is state-full and versioned, so a lost one is superseded by
        the next — the reference syncer's ack-free stream."""
        reply = self._apply_update_resources(p)
        if msgid is not None:
            conn.reply_nowait(msgid, "UpdateResources", reply)

    def _apply_update_resources(self, p: dict) -> dict:
        node = self.nodes.get(p["node_id"])
        if node is not None:
            rv = p.get("version")
            if rv is not None and rv <= node.report_version:
                # Out-of-order/stale report (reference: syncer drops
                # messages older than the last accepted version).
                return {"ok": True, "stale": True}
            if rv is not None:
                node.report_version = rv
            total_changed = bool(p.get("total")) and node.total != p["total"]
            changed = node.available != p["available"] or total_changed
            node.available = p["available"]
            node.last_seen = time.monotonic()
            if p.get("total"):
                node.total = p["total"]
            if changed:
                # No-change heartbeats (idle 1s reports) must not fan out
                # O(N^2) deltas across the cluster.
                self._bump_view(node, membership=total_changed)
                self._wake_scheduler.set()
        return {"ok": True}

    def _on_disconnect(self, conn: rpc.Connection) -> None:
        self._ship_subs.discard(conn)
        if self._stopping:
            return
        node_id = conn.context.get("node_id")
        if node_id and node_id in self.nodes:
            try:
                asyncio.get_running_loop()
                rpc.spawn(self._handle_node_death(node_id))
            except RuntimeError:
                pass  # loop already stopped (interpreter shutdown)
        self.publisher.remove_subscriber(conn)

    async def _handle_node_death(self, node_id: str, graceful: bool = False) -> None:
        node = self.nodes.get(node_id)
        if node is None or node.state == NODE_DEAD:
            return
        node.state = NODE_DEAD
        if graceful:
            logger.info("node %s unregistered (graceful shutdown)", node_id[:8])
        else:
            logger.warning("node %s died", node_id[:8])
        self.events.emit(
            "NODE_REMOVED",
            f"node {node_id[:8]} {'unregistered' if graceful else 'died'}",
            severity="INFO" if graceful else "WARNING",
            node_id=node_id,
            graceful=graceful,
        )
        self._publish_msg(
            "nodes",
            {
                "event": "removed",
                "node": node.to_wire(),
                # Object-location hint: every plasma copy addressed at this
                # raylet died with the node. Owners subscribed to "nodes"
                # match their IN_PLASMA markers against it and kick lineage
                # reconstruction eagerly (reference: object directory
                # location eviction on node removal).
                "lost_object_addr": list(node.addr),
            },
        )
        self._bump_view(node, membership=True)
        # Fail/restart actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ALIVE, PENDING_CREATION, RESTARTING):
                await self._on_actor_worker_death(actor, f"node {node_id[:8]} died")
        # PGs with bundles there go back to pending.
        for pg in self.placement_groups.values():
            if pg.state == PG_CREATED and node_id in pg.bundle_nodes:
                pg.state = PG_RESCHEDULING
                self._spawn_pg_schedule(pg)

    # -- actor FSM ----------------------------------------------------------

    async def _create_actor(self, conn, p):
        spec = p["spec"]
        actor_id = spec["actor_id"]
        # Idempotent upsert: a retried CreateActor (e.g. across a GCS
        # restart, where the reply was lost) must not double-enqueue or
        # collide with its own name registration.
        existing_self = self.actors.get(actor_id)
        if existing_self is not None:
            if p.get("wait_alive", True) and existing_self.state in (
                PENDING_CREATION,
                RESTARTING,
            ):
                fut = asyncio.get_running_loop().create_future()
                existing_self.pending.append(fut)
                # actor.pending futures are flushed on every FSM transition
                # (ALIVE, restart, death, node death); creation legitimately
                # outwaits cluster scale-up, and callers bound the wait with
                # their own rpc_actor_create_timeout_s budget.
                return await fut  # rpc-flow: disable=unbounded-await
            return {"actor": existing_self.to_wire()}
        actor = ActorInfo(actor_id, spec)
        if actor.name:
            key = (actor.namespace, actor.name)
            if key in self.named_actors and self.named_actors[key] != actor_id:
                existing_id = self.named_actors[key]
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != DEAD:
                    if p.get("get_if_exists"):
                        return {"existing": True, "actor": existing.to_wire()}
                    raise rpc.RpcError(f"actor name {actor.name!r} already taken")
            self.named_actors[key] = actor_id
            self._persist_named()
        self.actors[actor_id] = actor
        self._persist_actor(actor)
        # Keyed-guarded: a retried CreateActor returns from the idempotent
        # upsert branch above (self.actors membership) before reaching this
        # append, so the queue cannot double-enqueue.
        self._pending_actor_queue.append(actor_id)  # exc-flow: disable=retry-unsafe-mutation
        self._wake_scheduler.set()
        if p.get("wait_alive", True):
            fut = asyncio.get_running_loop().create_future()
            actor.pending.append(fut)
            # Same contract as the upsert branch above: pending futures are
            # flushed on every actor FSM transition, callers own the budget.
            return await fut  # rpc-flow: disable=unbounded-await
        return {"actor": actor.to_wire()}

    async def _actor_scheduler_loop(self) -> None:
        """Places pending actors on nodes as resources allow (analog of
        GcsActorScheduler). Placements run CONCURRENTLY under a bounded
        semaphore — each placement awaits a full lease -> worker spawn ->
        CreateActor round trip, and serializing those would make N actors
        cost N round trips of wall clock (the reference scheduler also
        leases in parallel). Runs whenever resources or the queue change."""
        sem = asyncio.Semaphore(64)
        placing: set = set()

        async def place_one(actor_id: str) -> None:
            async with sem:
                actor = self.actors.get(actor_id)
                if actor is None or actor.state not in (
                    PENDING_CREATION, RESTARTING,
                ):
                    placing.discard(actor_id)
                    return
                try:
                    placed = await self._try_place_actor(actor)
                except Exception:
                    # An unexpected error (e.g. a lease RPC timing out
                    # under extreme load) must requeue the actor, never
                    # kill placement — every pending actor depends on it.
                    logger.exception(
                        "placing actor %s failed; will retry", actor_id[:8]
                    )
                    placed = False
                placing.discard(actor_id)
                if not placed:
                    await asyncio.sleep(0.2)  # resources busy; retry paced
                    self._pending_actor_queue.append(actor_id)
                    self._wake_scheduler.set()

        while True:
            await self._wake_scheduler.wait()
            self._wake_scheduler.clear()
            queue, self._pending_actor_queue = self._pending_actor_queue, []
            requeue: List[str] = []
            for actor_id in queue:
                if actor_id in placing:
                    # A placement for this actor is already in flight; the
                    # event behind this entry (e.g. a second death) must
                    # not be dropped — re-examine it next round.
                    requeue.append(actor_id)
                    continue
                placing.add(actor_id)
                rpc.spawn(place_one(actor_id))
            if requeue:
                self._pending_actor_queue.extend(requeue)
                await asyncio.sleep(0.2)
                self._wake_scheduler.set()

    async def _try_place_actor(self, actor: ActorInfo) -> bool:
        demand = ResourceSet.from_units(actor.spec.get("resources") or {})
        strategy = actor.spec.get("scheduling_strategy") or {}
        candidates = [n for n in self.nodes.values() if n.state == NODE_ALIVE]
        if strategy.get("node_id"):
            candidates = [n for n in candidates if n.node_id == strategy["node_id"]]
        labels = strategy.get("labels")
        if labels:
            # NODE_LABEL actor placement (reference: GcsActorScheduler +
            # scheduling_options.h NODE_LABEL): hard gates, soft prefers.
            from ray_tpu.util.scheduling_strategies import node_matches_labels

            hard = labels.get("hard") or {}
            soft = labels.get("soft") or {}
            candidates = [
                n for n in candidates if node_matches_labels(hard, n.labels)
            ]
            if soft:
                preferred = [
                    n for n in candidates if node_matches_labels(soft, n.labels)
                ]
                candidates = preferred or candidates
        if actor.spec.get("pg_id"):
            pg = self.placement_groups.get(actor.spec["pg_id"])
            if pg is None or pg.state != PG_CREATED:
                return False
            idx = actor.spec.get("bundle_index", -1)
            nodes_ok = set(
                pg.bundle_nodes if idx < 0 else [pg.bundle_nodes[idx]]
            )
            candidates = [n for n in candidates if n.node_id in nodes_ok]
        feasible = [
            n
            for n in candidates
            if demand.is_subset_of(ResourceSet.from_units(n.total))
        ]
        if not feasible:
            if not candidates and strategy.get("node_id"):
                await self._fail_actor(actor, "node affinity target not found")
                return True
            return False
        available = [
            n
            for n in feasible
            if demand.is_subset_of(ResourceSet.from_units(n.available))
        ]
        if not available:
            return False
        # Pack: most-utilized feasible node first (reference hybrid policy).
        node = max(available, key=lambda n: _utilization(n))
        try:
            reply = await node.conn.call(
                "LeaseWorkerForActor",
                {"spec": actor.spec},
                timeout=config.rpc_lease_timeout_s,
            )
        except (rpc.RpcError, asyncio.TimeoutError) as e:
            # On timeout the raylet may still hold the queued lease: cancel
            # it so the requeued placement can't double-create the actor.
            try:
                await node.conn.call(
                    "CancelWorkerLease",
                    {"lease_id": "actor:" + actor.spec["actor_id"]},
                    timeout=config.rpc_control_timeout_s,
                )
            except Exception:
                pass
            logger.warning("actor lease on %s failed: %r", node.node_id[:8], e)
            return False
        if not reply.get("granted"):
            return False
        actor.node_id = node.node_id
        actor.worker_id = reply["worker_id"]
        return True

    async def _report_actor_ready(self, conn, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        if p.get("error"):
            await self._fail_actor(actor, p["error"], creation_failed=True)
            return {"ok": True}
        actor.state = ALIVE
        telemetry.record_event(
            "gcs", "actor_state", actor_id=actor.actor_id, state=ALIVE
        )
        actor.addr = tuple(p["addr"])
        actor.worker_id = p["worker_id"]
        actor.node_id = p["node_id"]
        self._persist_actor(actor)
        result = {"actor": actor.to_wire()}
        for fut in actor.pending:
            if not fut.done():
                fut.set_result(result)
        actor.pending.clear()
        self._publish_msg(f"actor:{actor.actor_id}", actor.to_wire())
        return {"ok": True}

    async def _on_actor_worker_death(self, actor: ActorInfo, cause: str) -> None:
        if actor.state == DEAD:
            return
        if actor.max_restarts == -1 or actor.num_restarts < actor.max_restarts:
            actor.num_restarts += 1
            actor.state = RESTARTING
            telemetry.record_event(
                "gcs",
                "actor_state",
                actor_id=actor.actor_id,
                state=RESTARTING,
                cause=cause,
            )
            actor.addr = None
            logger.info(
                "restarting actor %s (%d/%s): %s",
                actor.actor_id[:8],
                actor.num_restarts,
                actor.max_restarts,
                cause,
            )
            self._persist_actor(actor)
            self._publish_msg(f"actor:{actor.actor_id}", actor.to_wire())
            # Keyed-guarded: a retried ReportWorkerDied sees the actor
            # already RESTARTING (caller filters on ALIVE/PENDING_CREATION)
            # and never re-enters this branch.
            self._pending_actor_queue.append(actor.actor_id)  # exc-flow: disable=retry-unsafe-mutation
            self._wake_scheduler.set()
            self.events.emit(
                "ACTOR_RESTARTING",
                f"actor {actor.actor_id[:8]} restarting "
                f"({actor.num_restarts}/{actor.max_restarts}): {cause}",
                severity="WARNING",
                actor_id=actor.actor_id,
                cause=cause,
            )
        else:
            await self._fail_actor(actor, cause)

    async def _fail_actor(self, actor: ActorInfo, cause: str, creation_failed=False) -> None:
        actor.state = DEAD
        telemetry.record_event(
            "gcs", "actor_state", actor_id=actor.actor_id, state=DEAD, cause=cause
        )
        self.events.emit(
            "ACTOR_DEAD",
            f"actor {actor.actor_id[:8]} died: {cause}",
            # Deliberate kills are lifecycle, not failures.
            severity="INFO" if "ray.kill" in cause else "ERROR",
            actor_id=actor.actor_id,
            cause=cause,
        )
        actor.death_cause = cause
        # Write-through BEFORE acking waiters or publishing: a crash in the
        # window would hand callers a DEAD outcome that a restarted GCS
        # reloads as ALIVE/PENDING (exc_flow ack-before-persist).
        if actor.name and self.named_actors.get((actor.namespace, actor.name)) == actor.actor_id:
            del self.named_actors[(actor.namespace, actor.name)]
            self._persist_named()
        self._persist_actor(actor)
        for fut in actor.pending:
            if not fut.done():
                if creation_failed:
                    fut.set_exception(rpc.RpcError(f"actor creation failed: {cause}"))
                else:
                    fut.set_result({"actor": actor.to_wire()})
        actor.pending.clear()
        self._publish_msg(f"actor:{actor.actor_id}", actor.to_wire())

    async def _report_worker_died(self, conn, p):
        """Raylet reports a worker process exit (reference:
        WorkerInfoGcsService.ReportWorkerFailure)."""
        for actor_id in p.get("actor_ids", []):
            actor = self.actors.get(actor_id)
            if actor is not None and actor.state in (ALIVE, PENDING_CREATION):
                await self._on_actor_worker_death(
                    actor, p.get("cause") or "worker process died"
                )
        return {"ok": True}

    async def _report_deadline_stats(self, conn, p):
        """Accumulate a worker's deadline-enforcement deltas (worker-side
        rpc.deadline_stats snapshot-and-reset, flushed periodically and on
        exit by worker_main). Overruns carry the worker id so a violation
        names the process that outlived its deadline."""
        agg = self.worker_deadline_stats
        agg["met"] += int(p.get("met", 0))
        agg["shed"] += int(p.get("shed", 0))
        agg["enforced"] += int(p.get("enforced", 0))
        wid = p.get("worker_id", "?")
        for method, late in p.get("overruns", []):
            agg["overruns"].append((wid, method, float(late)))
        return {"ok": True}

    async def _report_telemetry(self, conn, p):
        """Fold one process's runtime-telemetry flush (additive counter/
        histogram deltas, gauge last-values, drained flight-recorder
        events) into the cluster aggregate. RETRY_NONE like
        ReportDeadlineStats: a dropped report rides the sender's next
        flush instead of being re-issued."""
        telemetry.ingest(self.telemetry, {"node": p["node"], "metrics": p["metrics"]})
        src = p["source"]
        for ts, comp, ev, fields in p.get("events", []):
            fields = dict(fields)
            fields.setdefault("source", src)
            self.flight_events.append((ts, comp, ev, fields))
        return {"ok": True}

    def _drain_local_telemetry(self) -> None:
        """Fold this process's own registry into the aggregate. Covers a
        GCS running without any co-resident flusher; when a flusher IS
        active in this process (in-process raylet/driver), it owns the
        drain — snapshot-and-reset makes either owner exactly-once."""
        if telemetry.flusher_active():
            return
        payload = telemetry.flush_delta("gcs", "gcs")
        if payload is None:
            return
        telemetry.ingest(self.telemetry, payload)
        for ts, comp, ev, fields in payload.get("events", []):
            fields = dict(fields)
            fields.setdefault("source", "gcs")
            self.flight_events.append((ts, comp, ev, fields))

    async def _get_telemetry(self, conn, p):
        """The runtime-metric aggregate plus the deadline-stats aggregate
        (dashboard /metrics render input)."""
        self._drain_local_telemetry()
        wds = self.worker_deadline_stats
        return {
            "telemetry": self.telemetry,
            "worker_deadline_stats": {
                "met": wds["met"],
                "shed": wds["shed"],
                "enforced": wds["enforced"],
                "overruns": [list(o) for o in wds["overruns"]],
            },
        }

    async def _get_actor(self, conn, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"actor": None}
        return {"actor": actor.to_wire()}

    async def _get_named_actor(self, conn, p):
        actor_id = self.named_actors.get((p.get("namespace") or "default", p["name"]))
        if actor_id is None:
            return {"actor": None}
        return {"actor": self.actors[actor_id].to_wire()}

    async def _list_actors(self, conn, p):
        return {"actors": [a.to_wire() for a in self.actors.values()]}

    async def _list_named_actors(self, conn, p):
        """Live named actors, optionally filtered by namespace (parity:
        ray.util.list_named_actors)."""
        ns_filter = p.get("namespace")
        names = []
        for (ns, name), actor_id in self.named_actors.items():
            actor = self.actors.get(actor_id)
            if actor is None or actor.state == DEAD:
                continue
            if ns_filter is not None and ns != ns_filter:
                continue
            names.append(name if ns_filter is not None else f"{ns}:{name}")
        return {"names": names}

    async def _kill_actor(self, conn, p):
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return {"ok": False}
        no_restart = p.get("no_restart", True)
        if no_restart:
            actor.max_restarts = actor.num_restarts  # exhaust restarts
            self._persist_actor(actor)
        node = self.nodes.get(actor.node_id) if actor.node_id else None
        if node is not None and node.state == NODE_ALIVE and actor.worker_id:
            try:
                await node.conn.call(
                    "KillWorker",
                    {"worker_id": actor.worker_id, "force": True},
                    timeout=config.rpc_control_timeout_s,
                )
            except rpc.RpcError:
                pass
        if no_restart and actor.state != DEAD:
            await self._fail_actor(actor, "killed via ray.kill")
        return {"ok": True}

    # -- kv -----------------------------------------------------------------

    async def _kv_put(self, conn, p):
        key = (p.get("ns") or "", p["key"])
        if not p.get("overwrite", True) and key in self.kv:
            return {"added": False}
        self.kv[key] = p["value"]
        self._persist_kv(key[0], key[1], p["value"])
        return {"added": True}

    async def _kv_get(self, conn, p):
        return {"value": self.kv.get((p.get("ns") or "", p["key"]))}

    async def _kv_del(self, conn, p):
        ns = p.get("ns") or ""
        if p.get("prefix"):
            keys = [k for k in self.kv if k[0] == ns and k[1].startswith(p["key"])]
            for k in keys:
                del self.kv[k]
                self._persist_kv(k[0], k[1], None)
            return {"deleted": len(keys)}
        removed = self.kv.pop((ns, p["key"]), None) is not None
        if removed:
            self._persist_kv(ns, p["key"], None)
        return {"deleted": int(removed)}

    async def _kv_keys(self, conn, p):
        ns = p.get("ns") or ""
        prefix = p.get("prefix") or ""
        return {"keys": [k[1] for k in self.kv if k[0] == ns and k[1].startswith(prefix)]}

    async def _kv_exists(self, conn, p):
        return {"exists": (p.get("ns") or "", p["key"]) in self.kv}

    # -- pubsub -------------------------------------------------------------

    async def _subscribe(self, conn, p):
        seq = self.publisher.subscribe(p["channel"], conn)
        # The current channel seqno is the subscriber's gap-detection
        # baseline: a resubscribing client compares it with the last seq it
        # saw and pulls a snapshot if publishes happened in between. The
        # epoch distinguishes "same publisher, you missed n messages" from
        # "new publisher (GCS restart), seqs restarted — resync".
        return {
            "ok": True,
            "seq": seq,
            "pub_epoch": self.publisher.epoch,
            "leader_term": self.leader_term,
        }

    async def _unsubscribe(self, conn, p):
        self.publisher.unsubscribe(p["channel"], conn)
        return {"ok": True}

    async def _publish(self, conn, p):
        self._publish_msg(p["channel"], p["msg"])
        return {"ok": True}

    async def _snapshot(self, conn, p):
        """Current state behind a pubsub channel, in the same shape a
        publish on that channel carries — what a subscriber that detected
        a seq gap (dropped backlog here, or a missed window across a
        reconnect) pulls to resynchronize instead of trusting a stale
        picture. Channels that carry events rather than state (e.g.
        "nodes", "logs") have no snapshot and return None; their consumers
        resync via their own full reads (GetAllNodes)."""
        channel = p["channel"]
        snap = None
        if channel.startswith("actor:"):
            actor = self.actors.get(channel[len("actor:"):])
            snap = None if actor is None else actor.to_wire()
        elif channel.startswith("pg:"):
            pg = self.placement_groups.get(channel[len("pg:"):])
            snap = None if pg is None else {"state": pg.state}
        elif channel == "syncer:nodes":
            snap = self._view_head_msg()
        return {
            "snapshot": snap,
            "seq": self.publisher.seqnos.get(channel, 0),
            "pub_epoch": self.publisher.epoch,
            "leader_term": self.leader_term,
        }

    def _publish_msg(self, channel: str, msg: Any) -> None:
        """Non-blocking fan-out: per-subscriber bounded queues + dedicated
        drain tasks (a slow subscriber drops ITS backlog, never stalls the
        control plane). Under HA every control-plane record carries the
        leader term, so a subscriber can drop a stale pre-failover message
        that arrives after it has seen the new leader."""
        if self.leader_term and isinstance(msg, dict):
            msg = {**msg, "leader_term": self.leader_term}
        self.publisher.publish(channel, msg)

    # -- jobs ---------------------------------------------------------------

    async def _register_job(self, conn, p):
        self.jobs[p["job_id"]] = {
            "job_id": p["job_id"],
            "driver_addr": p.get("driver_addr"),
            "start_time": time.time(),
            "state": "RUNNING",
            "entrypoint": p.get("entrypoint", ""),
        }
        self._persist_job(p["job_id"])
        return {"ok": True}

    async def _job_finished(self, conn, p):
        job = self.jobs.get(p["job_id"])
        if job:
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
            self._persist_job(p["job_id"])
        # Kill non-detached actors owned by the job.
        for actor in list(self.actors.values()):
            if actor.job_id == p["job_id"] and not actor.detached and actor.state != DEAD:
                await self._kill_actor(conn, {"actor_id": actor.actor_id, "no_restart": True})
        return {"ok": True}

    async def _list_jobs(self, conn, p):
        return {"jobs": list(self.jobs.values())}

    # -- placement groups (2PC driver; reference gcs_placement_group_scheduler.cc)

    async def _create_pg(self, conn, p):
        spec = PlacementGroupSpec.from_wire(p["spec"])
        pg = PlacementGroupInfo(spec)
        self.placement_groups[spec.pg_id] = pg
        self._persist_pg(pg)
        self._spawn_pg_schedule(pg)
        if p.get("wait_ready"):
            fut = asyncio.get_running_loop().create_future()
            pg.pending.append(fut)
            # pg.pending futures are resolved by _schedule_pg on creation,
            # infeasibility (PG_INFEASIBLE after its 120 s horizon), removal,
            # and — via _spawn_pg_schedule supervision — scheduler crashes.
            return await fut  # rpc-flow: disable=unbounded-await
        return {"pg_id": spec.pg_id, "state": pg.state}

    async def _schedule_pg(self, pg: PlacementGroupInfo) -> None:
        spec = pg.spec
        deadline = time.monotonic() + 120
        while pg.state in (PG_PENDING, PG_RESCHEDULING):
            placement = self._place_bundles(spec)
            if placement is not None:
                ok = await self._try_commit_pg(pg, placement)
                if pg.state == PG_REMOVED:
                    # Removed while the 2PC was in flight: drop the fresh
                    # reservations instead of resurrecting the PG.
                    if ok:
                        for nid in set(placement):
                            node = self.nodes.get(nid)
                            if node and node.state == NODE_ALIVE:
                                try:
                                    await node.conn.call(
                                        "ReleasePGBundles",
                                        {"pg_id": spec.pg_id},
                                        timeout=config.rpc_pg_timeout_s,
                                    )
                                except rpc.RpcError:
                                    pass
                    return
                if ok:
                    pg.state = PG_CREATED
                    pg.bundle_nodes = placement
                    self._persist_pg(pg)
                    for fut in pg.pending:
                        if not fut.done():
                            fut.set_result({"pg_id": spec.pg_id, "state": PG_CREATED})
                    pg.pending.clear()
                    self._publish_msg(f"pg:{spec.pg_id}", {"state": PG_CREATED})
                    self._wake_scheduler.set()
                    return
            if time.monotonic() > deadline:
                break
            await asyncio.sleep(0.2)
        if pg.state in (PG_PENDING, PG_RESCHEDULING):
            # Record terminal state so later WaitPlacementGroupReady calls
            # fail fast instead of parking a future nothing will resolve.
            pg.state = PG_INFEASIBLE
            self._persist_pg(pg)
            for fut in pg.pending:
                if not fut.done():
                    fut.set_exception(
                        rpc.RpcError(f"placement group {spec.pg_id[:8]} infeasible")
                    )
            pg.pending.clear()

    def _place_bundles(self, spec: PlacementGroupSpec) -> Optional[List[str]]:
        """Map bundles to nodes per strategy against the current resource view.
        Reference: bundle_scheduling_policy.cc (PACK/SPREAD/STRICT_*)."""
        alive = [n for n in self.nodes.values() if n.state == NODE_ALIVE]
        if not alive:
            return None
        avail = {n.node_id: ResourceSet.from_units(n.available) for n in alive}
        demands = [ResourceSet.from_units(b) for b in spec.bundles]
        placement: List[Optional[str]] = [None] * len(demands)

        def fits(nid, demand):
            return demand.is_subset_of(avail[nid])

        order = sorted(avail, key=lambda nid: -_utilization(self.nodes[nid]))
        if spec.strategy == "STRICT_PACK":
            for nid in order:
                total = ResourceSet()
                for d in demands:
                    total = total + d
                if total.is_subset_of(avail[nid]):
                    return [nid] * len(demands)
            return None
        if spec.strategy == "STRICT_SPREAD":
            if len(alive) < len(demands):
                return None
            used: Set[str] = set()
            for i, d in enumerate(demands):
                pick = next(
                    (nid for nid in order if nid not in used and fits(nid, d)), None
                )
                if pick is None:
                    return None
                placement[i] = pick
                used.add(pick)
                avail[pick] = avail[pick] - d
            return placement  # type: ignore[return-value]
        # PACK: prefer filling utilized nodes; SPREAD: prefer emptiest first.
        if spec.strategy == "SPREAD":
            order = list(reversed(order))
        for i, d in enumerate(demands):
            pick = next((nid for nid in order if fits(nid, d)), None)
            if pick is None:
                return None
            placement[i] = pick
            avail[pick] = avail[pick] - d
            if spec.strategy == "SPREAD":
                order.remove(pick)
                order.append(pick)  # round-robin
        return placement  # type: ignore[return-value]

    async def _try_commit_pg(self, pg: PlacementGroupInfo, placement: List[str]) -> bool:
        """Two-phase commit of bundle reservations across raylets."""
        spec = pg.spec
        by_node: Dict[str, List[int]] = {}
        for idx, nid in enumerate(placement):
            by_node.setdefault(nid, []).append(idx)
        prepared: List[str] = []
        for nid, idxs in by_node.items():
            node = self.nodes.get(nid)
            if node is None or node.state != NODE_ALIVE:
                break
            try:
                reply = await node.conn.call(
                    "PreparePGBundles",
                    {
                        "pg_id": spec.pg_id,
                        "bundles": {str(i): spec.bundles[i] for i in idxs},
                    },
                    timeout=config.rpc_pg_timeout_s,
                )
            except rpc.RpcError:
                break
            if not reply.get("success"):
                break
            prepared.append(nid)
        else:
            committed = True
            for nid in prepared:
                try:
                    await self.nodes[nid].conn.call(
                        "CommitPGBundles",
                        {"pg_id": spec.pg_id},
                        timeout=config.rpc_pg_timeout_s,
                    )
                except rpc.RpcError:
                    committed = False  # node died mid-commit: roll back all
                    break
            if committed:
                return True
        for nid in prepared:  # rollback
            try:
                await self.nodes[nid].conn.call(
                    "ReleasePGBundles",
                    {"pg_id": spec.pg_id},
                    timeout=config.rpc_pg_timeout_s,
                )
            except rpc.RpcError:
                pass
        return False

    async def _wait_pg_ready(self, conn, p):
        pg = self.placement_groups.get(p["pg_id"])
        if pg is None:
            raise rpc.RpcError(f"unknown placement group {p['pg_id'][:12]}")
        if pg.state == PG_CREATED:
            return {"pg_id": p["pg_id"], "state": PG_CREATED}
        if pg.state == PG_REMOVED:
            raise rpc.RpcError("placement group was removed")
        if pg.state == PG_INFEASIBLE:
            return {"pg_id": p["pg_id"], "state": PG_INFEASIBLE}
        fut = asyncio.get_running_loop().create_future()
        pg.pending.append(fut)
        if p.get("timeout") is not None:
            try:
                return await asyncio.wait_for(fut, p["timeout"])
            except asyncio.TimeoutError:
                if fut in pg.pending:
                    pg.pending.remove(fut)
                return {"pg_id": p["pg_id"], "state": pg.state}
        # pg.ready(timeout=None) is the blocking API: parking until the PG
        # reaches a terminal state is the contract. Every terminal path
        # resolves pg.pending — _schedule_pg success, _remove_pg, and the
        # _spawn_pg_schedule crash supervisor — so the future cannot strand.
        return await fut  # rpc-flow: disable=unbounded-await

    async def _remove_pg(self, conn, p):
        pg = self.placement_groups.get(p["pg_id"])
        if pg is None:
            return {"ok": False}
        pg.state = PG_REMOVED
        self._persist_pg(pg)
        # Wake any WaitPlacementGroupReady waiters parked while pending.
        for fut in pg.pending:
            if not fut.done():
                fut.set_exception(rpc.RpcError("placement group was removed"))
        pg.pending.clear()
        for nid in set(n for n in pg.bundle_nodes if n):
            node = self.nodes.get(nid)
            if node and node.state == NODE_ALIVE:
                try:
                    await node.conn.call(
                        "ReleasePGBundles",
                        {"pg_id": p["pg_id"]},
                        timeout=config.rpc_pg_timeout_s,
                    )
                except rpc.RpcError:
                    pass
        return {"ok": True}

    async def _get_pg(self, conn, p):
        pg = self.placement_groups.get(p["pg_id"])
        if pg is None:
            return {"pg": None}
        return {
            "pg": {
                "pg_id": pg.spec.pg_id,
                "state": pg.state,
                "strategy": pg.spec.strategy,
                "bundles": pg.spec.bundles,
                "bundle_nodes": pg.bundle_nodes,
                "name": pg.spec.name,
            }
        }

    async def _list_pgs(self, conn, p):
        return {
            "pgs": [
                (await self._get_pg(conn, {"pg_id": pid}))["pg"]
                for pid in self.placement_groups
            ]
        }

    # -- task events / status ----------------------------------------------

    async def _add_task_events(self, conn, p):
        for e in p["events"]:
            # Trace spans (state="SPAN" from make_submit_ctx/execute_scope)
            # live in their own ring beside the task-state events, so the
            # span store and task-event store trim independently and
            # ListSpans never scans lifecycle events.
            if e.get("state") == "SPAN":
                self.spans.append(e)
            else:
                self.task_events.append(e)
        if len(self.task_events) > 100000:
            self.task_events = self.task_events[-50000:]
        if len(self.spans) > 100000:
            self.spans = self.spans[-50000:]
        return {"ok": True}

    async def _list_task_events(self, conn, p):
        events = self.task_events
        if p.get("job_id"):
            events = [e for e in events if e.get("job_id") == p["job_id"]]
        return {"events": events[-(p.get("limit") or 1000):]}

    async def _report_spans(self, conn, p):
        """Fold one process's runtime-span flush into the span ring,
        stamping source attribution the way _report_telemetry stamps
        flight events. RETRY_NONE: an undelivered batch folds back into
        the sender's buffer and rides the next flush."""
        src, node = p["source"], p.get("node")
        for span in p["spans"]:
            span.setdefault("worker_id", src)
            if node is not None:
                span.setdefault("node_id", node)
            self.spans.append(span)
        if len(self.spans) > 100000:
            self.spans = self.spans[-50000:]
        return {"ok": True}

    def _drain_local_spans(self) -> None:
        """Fold this process's own span buffer into the ring at query time
        (freshness for in-process clusters). Skipped when a flusher is
        active here — it owns delivery; snapshot-and-reset makes either
        owner exactly-once."""
        from ray_tpu.util import tracing

        if tracing.flusher_active():
            return
        for span in tracing.span_flush_delta():
            span.setdefault("worker_id", "gcs")
            # Observability ring, not control-plane state: span_flush_delta
            # snapshots-and-resets, so a retried ListSpans drains an empty
            # delta; worst case is a duplicated trace row.
            self.spans.append(span)  # exc-flow: disable=retry-unsafe-mutation

    async def _list_spans(self, conn, p):
        """Server-side-filtered span read: the trace_id filter and limit
        run here, against the ring, so the client never receives the
        whole table (the satellite fix over the old ListTaskEvents
        scan-and-filter-client-side path)."""
        self._drain_local_spans()
        spans = self.spans
        if p.get("trace_id"):
            spans = [s for s in spans if s.get("trace_id") == p["trace_id"]]
        return {"spans": spans[-(p.get("limit") or 10000):]}

    async def _cluster_status(self, conn, p):
        return {
            "nodes": [n.to_wire() for n in self.nodes.values()],
            "actors": sum(1 for a in self.actors.values() if a.state == ALIVE),
            "placement_groups": sum(
                1 for g in self.placement_groups.values() if g.state == PG_CREATED
            ),
            "jobs": list(self.jobs.values()),
        }

    async def _ping(self, conn, p):
        return {"pong": True, "time": time.time()}


def _utilization(node: NodeInfo) -> float:
    util = 0.0
    for k, total in node.total.items():
        if total > 0:
            util = max(util, 1.0 - node.available.get(k, 0) / total)
    return util


class GcsClient:
    """Typed async client for the GCS (used by raylets, workers, drivers).

    Reconnecting: when the GCS restarts (fault-tolerance mode), the
    underlying ``rpc.RetryableConnection`` redials the same address with
    jittered backoff (``RetryPolicy.for_calls``), re-subscribes pubsub
    channels, fires registered ``on_reconnect`` callbacks (raylets
    re-register their node there), and transparently retries calls whose
    wire retry class permits it — every GCS handler is an idempotent
    upsert/read against keyed state, so the channel's default retry class
    is "safe". Analog of the reference's reconnect protocol around GCS
    restarts (NotifyGCSRestart, node_manager.proto:373; retryable gRPC
    client + gcs_rpc_client.h failover call queue)."""

    def __init__(self, conn: rpc.Connection, resolver=None):
        self.conn = conn
        self._resolver = resolver
        self._sub_handlers: Dict[str, List] = {}
        self._handlers = conn._handlers
        self._handlers.setdefault("Pub", self._on_pub)
        self._handlers.setdefault("PubBatch", self._on_pub_batch)
        # Sync fast path: pub deliveries dispatch inline from data_received
        # (no task per broadcast). The async registrations above stay as
        # fallback for connections without sync-handler support.
        self._sync_handlers = conn._sync_handlers
        self._sync_handlers.setdefault("Pub", self._on_pub_sync)
        self._sync_handlers.setdefault("PubBatch", self._on_pub_batch_sync)
        # Per-channel last-seen publish seqno + publisher epoch (gap
        # detection; see Publisher docstring and docs/fault_tolerance.md)
        # and leader term (HA: a term change is a new control plane — a
        # snapshot pull is mandatory even when epoch/seq happen to align).
        self._sub_seq: Dict[str, int] = {}
        self._sub_epoch: Dict[str, str] = {}
        self._sub_term: Dict[str, int] = {}
        self._on_reconnect: List = []
        # ``resolver``: async () -> (host, port) | None, consulted before
        # every redial so the client follows the current GCS leader across
        # failover instead of re-dialing the dead primary (gcs_ha.py).
        self._rc = rpc.RetryableConnection(
            self._redial,
            conn=conn,
            policy=rpc.RetryPolicy.for_calls(),
            default_retry=wire.RETRY_SAFE,
            on_reconnect=self._post_reconnect,
            name="gcs",
            resolver=resolver,
        )

    def on_reconnect(self, fn) -> None:
        """Register ``async fn(client)`` run after every successful redial."""
        self._on_reconnect.append(fn)

    @property
    def _closed(self) -> bool:
        return self._rc.closed

    async def close(self) -> None:
        """Terminal close: no reconnection afterwards. A stopping raylet must
        call this first, or a straggler RPC resurrects the 'dead' node in the
        GCS by re-registering through the reconnect path."""
        await self._rc.close()

    async def _redial(self, addr=None) -> rpc.Connection:
        addr = addr or self.conn.remote_addr or self.conn.peername
        if addr is None:
            raise rpc.ConnectionLost("gcs connection lost (no address to redial)")
        # With a resolver, each dial must give up fast: the resolved address
        # may be a dead primary whose leader file hasn't flipped yet, and
        # the resolver is only re-consulted between dial attempts — a 30s
        # dial budget would pin the dead address across the whole failover.
        # Without one the address is fixed, so patience is the right move
        # (a restarting GCS comes back on the same port).
        policy = (
            rpc.RetryPolicy.for_dial()
            if self._resolver is not None
            else rpc.RetryPolicy.for_calls()
        )
        conn = await rpc.connect(
            addr[0],
            addr[1],
            handlers=self._handlers,
            sync_handlers=self._sync_handlers,
            policy=policy,
        )
        conn.remote_addr = tuple(addr)
        return conn

    async def _post_reconnect(self, conn: rpc.Connection) -> None:
        # self.conn must point at the fresh link before the callbacks run:
        # they issue calls through this client (raylet re-registration).
        self.conn = conn
        for channel in list(self._sub_handlers):
            reply = await conn.call("Subscribe", {"channel": channel})
            self._check_resubscribe(channel, reply)
        for fn in self._on_reconnect:
            try:
                await fn(self)
            except Exception:
                logger.exception("gcs on_reconnect callback failed")
        addr = conn.remote_addr or conn.peername
        if addr is not None:
            logger.info("reconnected to gcs at %s:%s", *addr)

    def _check_resubscribe(self, channel: str, reply: dict) -> None:
        """Compare the resubscribe baseline with the last seq we saw: an
        advanced seq (missed publishes while disconnected) or a changed
        publisher epoch (GCS restart — seqs restarted from zero) both mean
        our picture may be stale, so pull a snapshot. A changed *leader
        term* (HA failover) is unconditionally stale: the new leader
        rebuilt its state from the replicated log, so even aligned seqnos
        describe a different history — the snapshot pull is mandatory."""
        seq, epoch = reply.get("seq"), reply.get("pub_epoch")
        term = reply.get("leader_term")
        if seq is None:
            return
        last = self._sub_seq.get(channel)
        last_term = self._sub_term.get(channel)
        stale = last is not None and (
            self._sub_epoch.get(channel) != epoch
            or seq > last
            or (term is not None and last_term is not None and term != last_term)
        )
        self._sub_seq[channel] = seq
        if epoch is not None:
            self._sub_epoch[channel] = epoch
        if term is not None:
            self._sub_term[channel] = term
        if stale:
            self._note_gap(channel, "resubscribe")

    async def _ensure_connected(self) -> rpc.Connection:
        return await self._rc._ensure_connected()

    def _on_pub_sync(self, conn, msgid, p):
        """Inline pub delivery from data_received — no task per push.
        Registered as a sync handler so a view-head broadcast costs zero
        task creations on each of N subscribers; async subscriber handlers
        still run (spawned), sync ones run inline."""
        self._dispatch_pub_sync(p["channel"], p["msg"], p.get("seq"))

    def _on_pub_batch_sync(self, conn, msgid, p):
        for channel, msg, seq in p["items"]:
            self._dispatch_pub_sync(channel, msg, seq)

    async def _on_pub(self, conn, p):
        await self._dispatch_pub(p["channel"], p["msg"], p.get("seq"))

    async def _on_pub_batch(self, conn, p):
        for channel, msg, seq in p["items"]:
            await self._dispatch_pub(channel, msg, seq)

    async def _dispatch_pub(self, channel: str, msg, seq) -> None:
        self._dispatch_pub_sync(channel, msg, seq)

    def _dispatch_pub_sync(self, channel: str, msg, seq) -> None:
        if isinstance(msg, dict) and "leader_term" in msg:
            term = msg["leader_term"]
            known = self._sub_term.get(channel)
            if known is not None and term < known:
                # Stale pre-failover message that outlived its leader
                # (buffered on the old link, delivered after promotion):
                # never deliver it — we already follow a newer term.
                self._note_gap(channel, "stale-term")
                return
            if known is None or term > known:
                self._sub_term[channel] = term
        if seq is not None:
            last = self._sub_seq.get(channel)
            if last is not None:
                if seq <= last:
                    return  # duplicate / already covered by a snapshot
                if seq > last + 1:
                    # The publisher shed part of OUR backlog (bounded-queue
                    # overflow): the stream is no longer a complete history,
                    # so resynchronize from a snapshot.
                    self._note_gap(channel, "overflow")
            self._sub_seq[channel] = seq
        self._deliver_sync(channel, msg)

    def _deliver_sync(self, channel: str, msg) -> None:
        for fn in list(self._sub_handlers.get(channel, [])):
            try:
                res = fn(msg)
                if asyncio.iscoroutine(res):
                    # Async subscriber handler: runs as its own task. Sync
                    # handlers (the hot view-head path) run inline.
                    rpc.spawn(res)
            except Exception:
                logger.exception("pubsub handler failed for %s", channel)

    async def _deliver(self, channel: str, msg) -> None:
        self._deliver_sync(channel, msg)

    def _note_gap(self, channel: str, cause: str) -> None:
        _TEL_SUB_GAP.cell(cause=cause).inc()
        logger.info("pubsub gap on %r (%s): pulling snapshot", channel, cause)
        rpc.spawn(self._pull_snapshot(channel))

    async def _pull_snapshot(self, channel: str) -> None:
        """Resync one channel: fetch the current state behind it and feed
        it to the handlers as if published. Channels without snapshot
        semantics return None (their consumers resync elsewhere)."""
        try:
            reply = await self.call("Snapshot", {"channel": channel})
        except (rpc.RpcError, asyncio.TimeoutError, OSError):
            logger.warning("snapshot pull for %r failed", channel)
            return
        seq, epoch = reply.get("seq"), reply.get("pub_epoch")
        if seq is not None and seq > self._sub_seq.get(channel, -1):
            self._sub_seq[channel] = seq
        if epoch is not None:
            self._sub_epoch[channel] = epoch
        term = reply.get("leader_term")
        if term is not None and term > self._sub_term.get(channel, -1):
            self._sub_term[channel] = term
        snap = reply.get("snapshot")
        if snap is not None:
            await self._deliver(channel, snap)

    async def subscribe(self, channel: str, handler, snapshot: bool = False) -> None:
        """Attach a handler. ``snapshot=True`` additionally delivers the
        channel's current state to THIS handler right after subscribing,
        closing the subscribe-after-publish race (the watcher that arrives
        late still observes the state it missed) — the general form of the
        one-shot GetActor the serve controller's death watch used to do."""
        fresh = channel not in self._sub_handlers
        self._sub_handlers.setdefault(channel, []).append(handler)
        conn = await self._ensure_connected()
        reply = await conn.call("Subscribe", {"channel": channel})
        seq, epoch = reply.get("seq"), reply.get("pub_epoch")
        if fresh and seq is not None:
            # Baseline only for a newly tracked channel: an existing
            # tracking regime may have deliveries in flight whose seqs a
            # forward jump here would wrongly mark as duplicates.
            self._sub_seq[channel] = seq
            if epoch is not None:
                self._sub_epoch[channel] = epoch
            if reply.get("leader_term") is not None:
                self._sub_term[channel] = reply["leader_term"]
        if snapshot:
            try:
                snap = (await self.call("Snapshot", {"channel": channel}))[
                    "snapshot"
                ]
            except (rpc.RpcError, asyncio.TimeoutError, OSError):
                snap = None
            if snap is not None:
                res = handler(snap)
                if asyncio.iscoroutine(res):
                    await res

    async def unsubscribe(self, channel: str, handler) -> None:
        """Detach one handler; drops the server-side subscription (and the
        reconnect re-subscribe) once the channel has no handlers left."""
        handlers = self._sub_handlers.get(channel)
        if handlers is None:
            return
        try:
            handlers.remove(handler)
        except ValueError:
            pass
        if handlers:
            return
        del self._sub_handlers[channel]
        conn = await self._ensure_connected()
        await conn.call("Unsubscribe", {"channel": channel})

    async def publish(self, channel: str, msg) -> None:
        await self.call("Publish", {"channel": channel, "msg": msg})

    async def kv_put(self, key: str, value: bytes, ns: str = "", overwrite=True) -> bool:
        r = await self.call(
            "KVPut", {"ns": ns, "key": key, "value": value, "overwrite": overwrite}
        )
        return r["added"]

    async def kv_get(self, key: str, ns: str = "") -> Optional[bytes]:
        return (await self.call("KVGet", {"ns": ns, "key": key}))["value"]

    async def kv_del(self, key: str, ns: str = "", prefix=False) -> int:
        return (await self.call("KVDel", {"ns": ns, "key": key, "prefix": prefix}))[
            "deleted"
        ]

    async def kv_exists(self, key: str, ns: str = "") -> bool:
        return (await self.call("KVExists", {"key": key, "ns": ns}))["exists"]

    async def kv_keys(self, prefix: str = "", ns: str = "") -> List[str]:
        return (await self.call("KVKeys", {"ns": ns, "prefix": prefix}))["keys"]

    async def call(self, method: str, payload=None, timeout=None):
        return await self._rc.call(method, payload, timeout)
