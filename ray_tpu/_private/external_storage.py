"""Pluggable external storage for object spilling.

Analog of python/ray/_private/external_storage.py: the raylet's local object
manager hands sealed objects to an ExternalStorage backend when the shm arena
fills, and reads them back on access. Backends are chosen by a JSON spilling
config (reference: ``RAY_object_spilling_config`` ``{"type": ..., "params":
...}``) and are registered by scheme so deployments can plug remote stores
(GCS buckets, NFS) without touching the raylet.

Unlike the reference (which forks dedicated IO-worker *processes*,
src/ray/raylet/local_object_manager.cc), IO here runs on a thread pool owned
by the raylet: spill/restore are pure byte copies that release the GIL inside
file read/write, so threads give the same event-loop isolation without
process-spawn cost.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Callable, Dict, Optional

from ray_tpu._private.common import config


class SpillIntegrityError(RuntimeError):
    """The bytes at a spill URI do not match the object that was written —
    e.g. a torn/partial upload from a crash mid-spill. Restore must raise
    this instead of returning short (callers would otherwise seal a buffer
    with trailing garbage); the raylet treats it as the copy being lost,
    not as a transient IO failure to retry."""

    def __init__(self, uri: str, expected: int, actual: int):
        super().__init__(
            f"spill file {uri} is torn: expected {expected} bytes, "
            f"storage holds {actual}"
        )
        self.uri = uri
        self.expected = expected
        self.actual = actual


class ExternalStorage:
    """One spill backend. Implementations must be thread-safe: the raylet
    calls spill/restore/delete concurrently from IO-pool threads."""

    def spill(self, oid: str, data: memoryview) -> str:
        """Write one object's bytes; returns an opaque URI for restore."""
        raise NotImplementedError

    def restore(self, uri: str, dest: memoryview) -> int:
        """Fill ``dest`` with the object at ``uri``; returns bytes read.

        Must raise SpillIntegrityError when storage holds fewer bytes than
        ``len(dest)`` (a torn spill file) rather than returning short."""
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        """Session teardown: drop everything this backend wrote."""


class FileSystemStorage(ExternalStorage):
    """Spill to a local directory; one file per object (reference:
    FileSystemStorage, external_storage.py:246)."""

    def __init__(self, directory_path: str):
        self.base = directory_path
        self._made = False
        self._lock = threading.Lock()

    def _ensure_dir(self) -> None:
        if not self._made:
            with self._lock:
                os.makedirs(self.base, exist_ok=True)
                self._made = True

    def spill(self, oid: str, data: memoryview) -> str:
        self._ensure_dir()
        # Unique per-spill filename: a stale fire-and-forget delete of a
        # prior generation's URI must never unlink a fresh re-spill.
        path = os.path.join(self.base, f"{oid}-{os.urandom(4).hex()}")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # readers never see partial writes
        return "file://" + path

    def restore(self, uri: str, dest: memoryview) -> int:
        path = uri[len("file://") :]
        n = 0
        with open(path, "rb") as f:
            while n < len(dest):
                got = f.readinto(dest[n:])
                if not got:
                    break
                n += got
        if n < len(dest):
            raise SpillIntegrityError(uri, len(dest), n)
        return n

    def delete(self, uri: str) -> None:
        try:
            os.unlink(uri[len("file://") :])
        except OSError:
            pass

    def destroy(self) -> None:
        shutil.rmtree(self.base, ignore_errors=True)


class UriStorage(ExternalStorage):
    """Spill to any pyarrow.fs URI — s3://bucket/prefix, gs://bucket/prefix,
    or file:///path (reference: external_storage.py:72 ExternalStorageURI /
    the smart_open-based remote backends). Credentials/endpoints resolve the
    standard way (AWS_* env incl. AWS_ENDPOINT_URL, GCE metadata), so the
    same config works against real object stores and the mock-S3 test
    server. A namespace subdir keeps raylets sharing a bucket apart."""

    def __init__(self, uri: str, namespace: str = ""):
        import pyarrow.fs as pafs

        self.uri = uri.rstrip("/")
        self.fs, base = pafs.FileSystem.from_uri(self.uri)
        self.base = base.rstrip("/")
        if namespace:
            self.base = f"{self.base}/{namespace}"
        self._ensured = False
        self._lock = threading.Lock()

    def _ensure(self) -> None:
        # Object stores don't need directories, but local/NFS through the
        # same API do; create_dir is a no-op where prefixes are virtual.
        if not self._ensured:
            with self._lock:
                if self._ensured:
                    return
                # Latch only on success: a transient create failure must be
                # retried by the next spill, not permanently swallowed.
                self.fs.create_dir(self.base, recursive=True)
                self._ensured = True

    def spill(self, oid: str, data: memoryview) -> str:
        self._ensure()
        key = f"{self.base}/{oid}-{os.urandom(4).hex()}"
        with self.fs.open_output_stream(key) as f:
            f.write(data)
        return "uri://" + key

    def restore(self, uri: str, dest: memoryview) -> int:
        key = uri[len("uri://") :]
        n = 0
        with self.fs.open_input_stream(key) as f:
            view = dest
            while n < len(view):
                chunk = f.read(len(view) - n)
                if not chunk:
                    break
                view[n : n + len(chunk)] = chunk
                n += len(chunk)
        if n < len(dest):
            # EOF before the buffer filled: the upload was torn (partial
            # write that a crash made visible). Distinguishable from a
            # transient stream error, which raises from pyarrow itself.
            raise SpillIntegrityError(uri, len(dest), n)
        return n

    def delete(self, uri: str) -> None:
        try:
            self.fs.delete_file(uri[len("uri://") :])
        except Exception:
            pass

    def destroy(self) -> None:
        try:
            self.fs.delete_dir_contents(self.base, missing_dir_ok=True)
        except Exception:
            pass


_REGISTRY: Dict[str, Callable[[dict], ExternalStorage]] = {
    "filesystem": lambda params: FileSystemStorage(**params),
    "uri": lambda params: UriStorage(**params),
}


def register_storage_backend(
    name: str, factory: Callable[[dict], ExternalStorage]
) -> None:
    """Register a spill backend under ``name`` so a spilling config
    ``{"type": name, "params": {...}}`` can select it — the hook remote
    storage (S3-style) implementations plug into."""
    _REGISTRY[name] = factory


def create_storage(
    spilling_config: str, default_dir: str, namespace: str = ""
) -> ExternalStorage:
    """Build the session's spill backend from the JSON spilling config, or a
    FileSystemStorage under ``default_dir`` when the config is empty.

    ``namespace`` (session+node scoped) is appended to any filesystem
    directory — including an explicitly configured one — so raylets sharing
    a mount never collide on files, and ``destroy()`` at node shutdown only
    removes this node's subtree."""
    if not spilling_config:
        return FileSystemStorage(default_dir)
    try:
        cfg = json.loads(spilling_config)
    except json.JSONDecodeError as e:
        raise ValueError(f"bad object_spilling_config: {e}") from e
    typ = cfg.get("type", "filesystem")
    factory = _REGISTRY.get(typ)
    if factory is None and ":" in typ:
        # Importable "pkg.mod:factory" types work in subprocess-mode raylets
        # too, where driver-side register_storage_backend() calls never ran
        # (reference: custom external storage via importable module path).
        import importlib

        mod_name, _, attr = typ.partition(":")
        factory = getattr(importlib.import_module(mod_name), attr)
    if factory is None:
        raise ValueError(
            f"unknown spill backend {typ!r}; registered: {sorted(_REGISTRY)} "
            "(or use an importable 'pkg.mod:factory' type)"
        )
    params = dict(cfg.get("params") or {})
    if typ == "filesystem":
        if "directory_path" in params and namespace:
            params["directory_path"] = os.path.join(
                params["directory_path"], namespace
            )
        params.setdefault("directory_path", default_dir)
    elif typ == "uri":
        params.setdefault("namespace", namespace)
    return factory(params)
