"""Unique identifiers for objects, tasks, actors, nodes, jobs, etc.

TPU-native analog of the reference's id scheme (src/ray/common/id.h): fixed-width
random binary IDs with cheap hashing and hex reprs. We keep a single width (16
bytes) for all ID kinds — the reference's varying widths (28/16/...) encode
lineage provenance in the bytes; we carry provenance explicitly in specs instead,
which keeps the ID type trivial and msgpack-friendly.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16


class BaseID:
    """A fixed-width binary id. Immutable, hashable, msgpack-serializable."""

    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != _ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_SIZE} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash((type(self).__name__, id_bytes))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class JobID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


_counter_lock = threading.Lock()
_counters: dict = {}

# Hot-path unique ids: a per-process random prefix + GIL-atomic counter is
# ~20x cheaper than os.urandom per id and just as collision-safe across
# processes (the 10-byte prefix is the entropy; the counter guarantees
# process-local uniqueness). Mirrors the reference's cached-entropy id
# generation in src/ray/common/id.h (JobID/TaskID compose a random root with
# deterministic counters).
_FAST_PREFIX = os.urandom(_ID_SIZE - 6).hex()
import itertools as _itertools

_fast_counter = _itertools.count(int.from_bytes(os.urandom(4), "little"))


def _reseed_after_fork() -> None:
    # Workers are os.fork()ed from a preloaded zygote (worker_zygote.py),
    # which imports this module BEFORE forking: without a reseed every
    # worker inherits the same prefix and counter position, so two workers
    # submitting tasks draw IDENTICAL task ids — and task ids feed
    # deterministic_object_id, so their return objects alias in the store
    # (ObjCreate sees `exists` and the second task's output is silently the
    # first task's bytes). Observed as flaky wrong-block delivery in the
    # data pipeline whenever two forked workers (e.g. two streaming-split
    # coordinators) ran near-aligned submission counts.
    global _FAST_PREFIX, _fast_counter
    _FAST_PREFIX = os.urandom(_ID_SIZE - 6).hex()
    _fast_counter = _itertools.count(int.from_bytes(os.urandom(4), "little"))


os.register_at_fork(after_in_child=_reseed_after_fork)


def fast_unique_hex() -> str:
    """A unique 32-char hex id (16 bytes), cheap enough for per-call use."""
    return _FAST_PREFIX + (next(_fast_counter) & 0xFFFFFFFFFFFF).to_bytes(6, "little").hex()


import hashlib as _hashlib
_blake2b = _hashlib.blake2b


def deterministic_object_id(task_id: TaskID, index: int) -> ObjectID:
    """Return objects of a task get deterministic ids derived from the task id,
    so lineage re-execution reproduces the same object ids (reference:
    ObjectID::FromIndex in src/ray/common/id.h)."""
    h = _blake2b(task_id.binary() + index.to_bytes(4, "little"), digest_size=_ID_SIZE)
    return ObjectID(h.digest())


def return_object_ids(task_id_hex: str, n: int) -> list:
    """Hex ids of the n return objects of a task (hot-path form of
    deterministic_object_id: no BaseID wrappers)."""
    tid = bytes.fromhex(task_id_hex)
    return [
        _blake2b(tid + i.to_bytes(4, "little"), digest_size=_ID_SIZE).hexdigest()
        for i in range(n)
    ]
