"""HA control plane: leadership records, leader resolution, warm standby.

The replicated store (gcs_store.ReplicatedStoreClient) gives the GCS a
log that survives machine loss; this module turns that into a highly
available control plane (reference: the GCS-backed-by-Redis deployment
plus its "who is leader" coordination, in miniature):

- **Leadership record**: the serving GCS writes ``meta/leadership`` —
  ``{term, deadline, host, port}`` — through the replicated store and
  renews it every third of ``gcs_leader_lease_s``. The write itself is
  the fencing primitive: it carries the writer's term, so a deposed
  leader's renewal bounces off the store's fence with StaleLeaderError
  and the GCS demotes (stops serving) instead of split-braining.
- **Warm standby** (``GcsStandby``): mirrors the leader's quorum-acked
  commit stream, watches the leadership record, and when the lease
  deadline expires unrenewed, promotes: claims the next term
  (gcs_store.try_claim_term — losers re-enter the watch loop) and builds
  a ``GcsServer`` over the replicated store at that term. Opening the
  store runs the quorum election: a majority of members must be
  reachable, and the highest (term, seq) among them is adopted — any ack
  quorum intersects any such majority, so every acknowledged record
  survives even when the single freshest file sits on an unreachable
  laggard. Opening also raises the fence on every reachable member
  before the first write, and the new server's fresh publisher epoch +
  term-stamped records drive every resubscribing client through a
  snapshot pull (docs/fault_tolerance.md).

  Two feed modes (``gcs_standby_mode``): ``"rpc"`` (default) subscribes
  to the leader over ShipFrames/ShipSnapshot wire RPCs — the standby can
  be its own OS process on another host (``python -m
  ray_tpu._private.gcs_ha``) — and falls back to file tailing while the
  leader is unreachable; ``"file"`` tails a follower log on shared
  storage (ReplicaTailer).
- **Leader pointer file**: ``<persist_path>.leader`` holds "host port",
  atomically replaced on every (re)election. ``file_resolver`` adapts it
  to RetryableConnection's pluggable resolver so raylets/workers re-dial
  the *current* leader, not the dead primary's address.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional, Tuple

import msgpack

from ray_tpu._private import telemetry
from ray_tpu._private.common import config

logger = logging.getLogger(__name__)

LEADERSHIP_TABLE = "meta"
LEADERSHIP_KEY = "leadership"

_TEL_ROLE = telemetry.gauge(
    "gcs", "role", "this process's GCS role: 1 leader, 0 standby/demoted"
)
_TEL_FAILOVERS = telemetry.counter(
    "gcs", "failovers", "standby promotions to leader"
)


def note_role(leader: bool) -> None:
    _TEL_ROLE.set(1.0 if leader else 0.0)


def note_failover() -> None:
    _TEL_FAILOVERS.inc()


# -- leadership record -------------------------------------------------------


def write_leadership(store, term: int, addr: Tuple[str, int]) -> None:
    """One lease assertion/renewal: term + fresh deadline, written through
    the (fencing) store. Raises StaleLeaderError if a newer leader exists."""
    rec = {
        "term": term,
        "deadline": time.time() + config.gcs_leader_lease_s,
        "host": addr[0],
        "port": addr[1],
    }
    store.put(
        LEADERSHIP_TABLE, LEADERSHIP_KEY, msgpack.packb(rec, use_bin_type=True)
    )
    # The record IS the lease: it must be on the followers before the
    # deadline means anything, not parked in the group-commit buffer.
    if hasattr(store, "flush"):
        store.flush()


def read_leadership(source) -> Optional[dict]:
    """Decode the leadership record from anything with ``get(table, key)``
    (a StoreClient or a ReplicaTailer)."""
    blob = source.get(LEADERSHIP_TABLE, LEADERSHIP_KEY)
    if not blob:
        return None
    return msgpack.unpackb(blob, raw=False)


# -- leader pointer file -----------------------------------------------------


def leader_file_path(persist_path: Optional[str]) -> Optional[str]:
    if config.gcs_leader_file:
        return config.gcs_leader_file
    if not persist_path:
        return None
    return persist_path + ".leader"


def write_leader_file(path: Optional[str], host: str, port: int) -> None:
    """Atomically publish the serving address (tmp + rename, so a reader
    never sees a half-written pointer)."""
    if not path:
        return
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
    os.replace(tmp, path)


def resolve_leader_file(path: Optional[str]) -> Optional[Tuple[str, int]]:
    if not path:
        return None
    try:
        with open(path) as f:
            host, port = f.read().split()
        return host, int(port)
    except (OSError, ValueError):
        return None


def file_resolver(path: Optional[str]):
    """RetryableConnection ``resolver`` over the leader pointer file; None
    (no file yet / unreadable) keeps the last known address."""

    async def _resolve() -> Optional[Tuple[str, int]]:
        return resolve_leader_file(path)

    return _resolve


# -- warm standby ------------------------------------------------------------


class _ShipMirror:
    """Standby-side state mirror fed by the leader's ShipFrames pushes:
    the cross-process analog of a follower applying its received stream.
    Same read interface as ReplicaTailer (``get``/``get_all``/``term``/
    ``seq``) so read_leadership works on either feed."""

    def __init__(self):
        self.tables: dict = {}
        self.term = 0
        self.seq = 0

    def apply_snapshot(self, snap: bytes, term: int, seq: int) -> None:
        self.tables = {
            t: dict(kv) for t, kv in msgpack.unpackb(snap, raw=False).items()
        }
        self.term = term
        self.seq = seq

    def apply_frames(self, data: bytes) -> None:
        from ray_tpu._private.gcs_store import apply_replicated

        self.tables, term, seq, _ = apply_replicated(self.tables, data)
        self.term = max(self.term, term)
        self.seq = max(self.seq, seq)

    def get(self, table: str, key: str):
        return self.tables.get(table, {}).get(key)

    def get_all(self, table: str) -> dict:
        return dict(self.tables.get(table, {}))


class GcsStandby:
    """Warm-standby GCS: mirrors the replicated log and promotes itself
    when the leader's lease expires unrenewed.

    The standby holds the whole control-plane state as a live mirror —
    fed over ShipFrames/ShipSnapshot RPCs from the leader (``mode="rpc"``,
    works across OS processes) or by tailing a follower log from shared
    storage (``mode="file"``); rpc mode falls back to the file tailer
    while the leader is unreachable. Promotion is therefore bounded by
    recovery *reconciliation* — requeueing in-flight actor/PG placements —
    not by replaying history. ``on_promote(server)`` fires after the new
    server is listening; ``promoted`` is set for waiters.

    Losing a promotion race (another standby claimed or fenced past us)
    re-enters the watch loop at the new term — the standby pool survives
    any number of consecutive failovers.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        session_name: str = "",
        persist_path: Optional[str] = None,
        on_promote=None,
        mode: Optional[str] = None,
    ):
        from ray_tpu._private.gcs_store import ReplicaTailer, follower_paths

        if not persist_path:
            raise ValueError("a standby requires a replicated persist path")
        self.host = host
        self.port = port
        self.session_name = session_name
        self.persist_path = persist_path
        self.mode = mode or config.gcs_standby_mode
        self.tailer = ReplicaTailer(follower_paths(persist_path)[0])
        self.mirror = _ShipMirror()
        # Stream-health counters (tests + debugging): frames/snapshots
        # received over the RPC feed.
        self.frames_received = 0
        self.snapshots_pulled = 0
        self.server = None  # GcsServer once promoted
        self.promoted = asyncio.Event()
        self._on_promote = on_promote
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self._conn = None  # ShipFrames subscription to the leader
        self._need_snapshot = False

    async def start(self) -> "GcsStandby":
        from ray_tpu._private import rpc

        note_role(leader=False)
        self.tailer.poll()
        self._task = rpc.spawn(self._watch_loop())
        return self

    # -- rpc feed ------------------------------------------------------------

    async def _on_ship_frames(self, conn, p: dict) -> None:
        """Client-side push handler: one quorum-acked group commit. A
        watermark gap (we missed a window across a reconnect) flags a
        snapshot re-pull instead of splicing a hole into the mirror."""
        if p["prev_seq"] != self.mirror.seq:
            self._need_snapshot = True
            return
        self.mirror.apply_frames(p["frames"])
        self.frames_received += 1

    async def _ensure_stream(self) -> bool:
        """Dial the current leader (pointer file) and (re)subscribe;
        returns True while the RPC feed is live. Any failure leaves the
        file tailer as the feed for this poll round."""
        from ray_tpu._private import rpc

        if self._conn is not None and not self._conn.closed:
            if self._need_snapshot:
                await self._pull_snapshot(self._conn)
            return True
        self._conn = None
        addr = resolve_leader_file(leader_file_path(self.persist_path))
        if addr is None:
            return False
        try:
            conn = await rpc.connect(
                addr[0],
                addr[1],
                handlers={"ShipFrames": self._on_ship_frames},
                retry=1,
            )
            sub = await conn.call(
                "ShipSubscribe", {}, timeout=config.gcs_leader_lease_s
            )
            if not sub.get("ok"):
                await conn.close()
                return False
            await self._pull_snapshot(conn)
            self._conn = conn
            return True
        except (rpc.RpcError, OSError, asyncio.TimeoutError):
            return False

    async def _pull_snapshot(self, conn) -> None:
        snap = await conn.call(
            "ShipSnapshot", {}, timeout=config.gcs_leader_lease_s
        )
        if snap.get("ok"):
            self.mirror.apply_snapshot(snap["snap"], snap["term"], snap["seq"])
            self.snapshots_pulled += 1
            self._need_snapshot = False

    def _view(self, streaming: bool):
        """The freshest feed for leadership-record reads this round."""
        if streaming and self.mirror.seq >= self.tailer.seq:
            return self.mirror
        return self.tailer

    # -- watch loop ----------------------------------------------------------

    async def _watch_loop(self) -> None:
        from ray_tpu._private import rpc
        from ray_tpu._private.gcs_store import try_claim_term

        grace = config.gcs_leader_lease_s / 3.0
        while not self._stopped:
            await asyncio.sleep(config.gcs_standby_poll_s)
            streaming = False
            if self.mode == "rpc":
                try:
                    streaming = await self._ensure_stream()
                except rpc.ConnectionLost:
                    streaming = False
            if not streaming:
                self.tailer.poll()
            view = self._view(streaming)
            rec = read_leadership(view)
            if rec is None:
                continue  # no leader has ever asserted: nothing to succeed
            if time.time() <= rec["deadline"] + grace:
                continue
            # Election round: claim the next term atomically so racing
            # standbys cannot both open the store at the same term. The
            # loser re-enters the loop and sees either the winner's renewed
            # lease or a later expiry at a higher term.
            term = max(rec["term"], view.term) + 1
            if not try_claim_term(self.persist_path, term):
                continue
            try:
                await self._promote(term)
                return
            except Exception:
                # Lost the race past the claim (fenced by a higher term) or
                # a majority of members is unreachable (QuorumLostError):
                # stay armed and re-enter the loop at the new term.
                logger.exception(
                    "standby promotion at term %d failed; re-arming", term
                )
                continue

    async def _promote(self, term: int) -> None:
        from ray_tpu._private.gcs import GcsServer

        logger.warning(
            "gcs leader lease expired: standby promoting at term %d", term
        )
        t0 = time.perf_counter()
        server = GcsServer(
            self.host,
            self.port,
            session_name=self.session_name,
            persist_path=self.persist_path,
            persist_backend="replicated",
            term=term,
        )
        await server.start()  # writes leadership record + leader file
        self.server = server
        note_failover()
        telemetry.record_event(
            "gcs", "failover", term=term, promote_s=time.perf_counter() - t0
        )
        self.promoted.set()
        if self._on_promote is not None:
            res = self._on_promote(server)
            if asyncio.iscoroutine(res):
                await res

    async def stop(self) -> None:
        """Stop watching; if promoted, the served GcsServer is stopped too."""
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._conn is not None:
            await self._conn.close()
            self._conn = None
        if self.server is not None:
            await self.server.stop()


# -- OS-process standby entrypoint -------------------------------------------
#
# Run a standby as its own process (its own host, in a real deployment):
#
#     python -m ray_tpu._private.gcs_ha --persist-path /path/to/gcs.db
#
# The process arms a GcsStandby (rpc mode by default: it dials the leader
# from the pointer file and mirrors the quorum-acked stream), promotes on
# lease expiry, then keeps serving as the leader until SIGTERM/SIGINT.


def _main(argv=None) -> None:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu._private.gcs_ha",
        description="Run a warm-standby GCS as its own OS process.",
    )
    ap.add_argument("--persist-path", required=True,
                    help="replicated store path of the group to stand by for")
    ap.add_argument("--session", default="standby")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--mode", choices=("rpc", "file"), default=None,
                    help="stream feed (default: the gcs_standby_mode knob)")
    args = ap.parse_args(argv)

    async def _run() -> None:
        standby = GcsStandby(
            args.host,
            args.port,
            session_name=args.session,
            persist_path=args.persist_path,
            mode=args.mode,
        )
        await standby.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await standby.stop()

    from ray_tpu._private import rpc

    rpc.install_event_loop()
    asyncio.run(_run())


if __name__ == "__main__":
    _main()
