"""HA control plane: leadership records, leader resolution, warm standby.

The replicated store (gcs_store.ReplicatedStoreClient) gives the GCS a
log that survives machine loss; this module turns that into a highly
available control plane (reference: the GCS-backed-by-Redis deployment
plus its "who is leader" coordination, in miniature):

- **Leadership record**: the serving GCS writes ``meta/leadership`` —
  ``{term, deadline, host, port}`` — through the replicated store and
  renews it every third of ``gcs_leader_lease_s``. The write itself is
  the fencing primitive: it carries the writer's term, so a deposed
  leader's renewal bounces off the store's fence with StaleLeaderError
  and the GCS demotes (stops serving) instead of split-braining.
- **Warm standby** (``GcsStandby``): tails a follower log from disk
  (ReplicaTailer — the cross-process analog of a follower applying its
  shipped stream), watches the leadership record, and when the lease
  deadline expires unrenewed, promotes: builds a ``GcsServer`` over the
  replicated store at ``term + 1``. Opening the store at the new term
  raises the fence on every member before the first write, and the new
  server's fresh publisher epoch + term-stamped records drive every
  resubscribing client through a snapshot pull (docs/fault_tolerance.md).
- **Leader pointer file**: ``<persist_path>.leader`` holds "host port",
  atomically replaced on every (re)election. ``file_resolver`` adapts it
  to RetryableConnection's pluggable resolver so raylets/workers re-dial
  the *current* leader, not the dead primary's address.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional, Tuple

import msgpack

from ray_tpu._private import telemetry
from ray_tpu._private.common import config

logger = logging.getLogger(__name__)

LEADERSHIP_TABLE = "meta"
LEADERSHIP_KEY = "leadership"

_TEL_ROLE = telemetry.gauge(
    "gcs", "role", "this process's GCS role: 1 leader, 0 standby/demoted"
)
_TEL_FAILOVERS = telemetry.counter(
    "gcs", "failovers", "standby promotions to leader"
)


def note_role(leader: bool) -> None:
    _TEL_ROLE.set(1.0 if leader else 0.0)


def note_failover() -> None:
    _TEL_FAILOVERS.inc()


# -- leadership record -------------------------------------------------------


def write_leadership(store, term: int, addr: Tuple[str, int]) -> None:
    """One lease assertion/renewal: term + fresh deadline, written through
    the (fencing) store. Raises StaleLeaderError if a newer leader exists."""
    rec = {
        "term": term,
        "deadline": time.time() + config.gcs_leader_lease_s,
        "host": addr[0],
        "port": addr[1],
    }
    store.put(
        LEADERSHIP_TABLE, LEADERSHIP_KEY, msgpack.packb(rec, use_bin_type=True)
    )
    # The record IS the lease: it must be on the followers before the
    # deadline means anything, not parked in the group-commit buffer.
    if hasattr(store, "flush"):
        store.flush()


def read_leadership(source) -> Optional[dict]:
    """Decode the leadership record from anything with ``get(table, key)``
    (a StoreClient or a ReplicaTailer)."""
    blob = source.get(LEADERSHIP_TABLE, LEADERSHIP_KEY)
    if not blob:
        return None
    return msgpack.unpackb(blob, raw=False)


# -- leader pointer file -----------------------------------------------------


def leader_file_path(persist_path: Optional[str]) -> Optional[str]:
    if config.gcs_leader_file:
        return config.gcs_leader_file
    if not persist_path:
        return None
    return persist_path + ".leader"


def write_leader_file(path: Optional[str], host: str, port: int) -> None:
    """Atomically publish the serving address (tmp + rename, so a reader
    never sees a half-written pointer)."""
    if not path:
        return
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
    os.replace(tmp, path)


def resolve_leader_file(path: Optional[str]) -> Optional[Tuple[str, int]]:
    if not path:
        return None
    try:
        with open(path) as f:
            host, port = f.read().split()
        return host, int(port)
    except (OSError, ValueError):
        return None


def file_resolver(path: Optional[str]):
    """RetryableConnection ``resolver`` over the leader pointer file; None
    (no file yet / unreadable) keeps the last known address."""

    async def _resolve() -> Optional[Tuple[str, int]]:
        return resolve_leader_file(path)

    return _resolve


# -- warm standby ------------------------------------------------------------


class GcsStandby:
    """Warm-standby GCS: tails the replicated log and promotes itself when
    the leader's lease expires unrenewed.

    The standby holds the whole control-plane state as a live mirror (the
    tailer applies every shipped frame as it lands), so promotion is
    bounded by recovery *reconciliation* — requeueing in-flight actor/PG
    placements — not by replaying history. ``on_promote(server)`` fires
    after the new server is listening; ``promoted`` is set for waiters.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        session_name: str = "",
        persist_path: Optional[str] = None,
        on_promote=None,
    ):
        from ray_tpu._private.gcs_store import ReplicaTailer, follower_paths

        if not persist_path:
            raise ValueError("a standby requires a replicated persist path")
        self.host = host
        self.port = port
        self.session_name = session_name
        self.persist_path = persist_path
        self.tailer = ReplicaTailer(follower_paths(persist_path)[0])
        self.server = None  # GcsServer once promoted
        self.promoted = asyncio.Event()
        self._on_promote = on_promote
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    async def start(self) -> "GcsStandby":
        from ray_tpu._private import rpc

        note_role(leader=False)
        self.tailer.poll()
        self._task = rpc.spawn(self._watch_loop())
        return self

    async def _watch_loop(self) -> None:
        grace = config.gcs_leader_lease_s / 3.0
        while not self._stopped:
            await asyncio.sleep(config.gcs_standby_poll_s)
            self.tailer.poll()
            rec = read_leadership(self.tailer)
            if rec is None:
                continue  # no leader has ever asserted: nothing to succeed
            if time.time() <= rec["deadline"] + grace:
                continue
            try:
                await self._promote(rec["term"] + 1)
            except Exception:
                # Lost the promotion race (another standby fenced past us)
                # or the store is gone; either way this standby is done.
                logger.exception("standby promotion at term %d failed",
                                 rec["term"] + 1)
            return

    async def _promote(self, term: int) -> None:
        from ray_tpu._private.gcs import GcsServer

        logger.warning(
            "gcs leader lease expired: standby promoting at term %d", term
        )
        t0 = time.perf_counter()
        server = GcsServer(
            self.host,
            self.port,
            session_name=self.session_name,
            persist_path=self.persist_path,
            persist_backend="replicated",
            term=term,
        )
        await server.start()  # writes leadership record + leader file
        self.server = server
        note_failover()
        telemetry.record_event(
            "gcs", "failover", term=term, promote_s=time.perf_counter() - t0
        )
        self.promoted.set()
        if self._on_promote is not None:
            res = self._on_promote(server)
            if asyncio.iscoroutine(res):
                await res

    async def stop(self) -> None:
        """Stop watching; if promoted, the served GcsServer is stopped too."""
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
        if self.server is not None:
            await self.server.stop()
