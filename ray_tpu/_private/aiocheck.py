"""Runtime asyncio interleaving probe (``RAY_TPU_AIOCHECK=1``).

A lightweight dynamic race detector for the single-loop control plane: the
GCS and raylet wrap their shared-state dicts in :class:`TrackedDict`, which
attributes every read/write to the ``asyncio.Task`` performing it.
:func:`conflicts` then reports the two hazard shapes the static
``await-interleave`` lint rule targets, observed for real:

- **read-await-write** (lost update): task A reads key K, task B writes K
  while A is suspended at an await, then A writes K back — A's write is
  based on a stale view.
- **write-write**: two different tasks write the same key with no
  intervening read by the later writer — last-writer-wins with neither
  side seeing the other.

Everything is loop-local and sequential (asyncio interleaves only at
awaits), so plain event recording with a global sequence number is exact —
no clocks or locks needed. Overhead when disabled is zero: ``track()``
returns the original dict unless ``RAY_TPU_AIOCHECK=1`` was set at process
start. Tests use this probe to validate the static pass: a seeded
interleaving bug must show up here (see tests/test_devtools_lint.py).
"""

from __future__ import annotations

import asyncio
import itertools
import os
from dataclasses import dataclass
from typing import Any, Dict, List, MutableMapping, Optional, Tuple


def enabled() -> bool:
    return os.environ.get("RAY_TPU_AIOCHECK") == "1"


_seq = itertools.count()
# (seq, task_label, op, dict_name, key); op is "r" or "w".
_events: List[Tuple[int, str, str, str, Any]] = []


def _task_label() -> str:
    try:
        task = asyncio.current_task()
    except RuntimeError:
        task = None
    if task is None:
        return "<no-task>"
    return task.get_name()


def _record(op: str, name: str, key: Any) -> None:
    try:
        hash(key)
    except TypeError:
        return
    _events.append((next(_seq), _task_label(), op, name, key))


class TrackedDict(dict):
    """dict proxy recording per-key reads/writes attributed to the current
    asyncio task. Whole-dict operations (iteration, len, values) are not
    treated as key reads — the hazard shapes are per-key."""

    def __init__(self, name: str, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._aiocheck_name = name

    # -- reads --------------------------------------------------------------

    def __getitem__(self, key):
        _record("r", self._aiocheck_name, key)
        return super().__getitem__(key)

    def get(self, key, default=None):
        _record("r", self._aiocheck_name, key)
        return super().get(key, default)

    def __contains__(self, key):
        _record("r", self._aiocheck_name, key)
        return super().__contains__(key)

    # -- writes -------------------------------------------------------------

    def __setitem__(self, key, value):
        _record("w", self._aiocheck_name, key)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        _record("w", self._aiocheck_name, key)
        super().__delitem__(key)

    def pop(self, key, *default):
        _record("w", self._aiocheck_name, key)
        return super().pop(key, *default)

    def setdefault(self, key, default=None):
        # Read-or-write in one atomic step; record as both.
        _record("r", self._aiocheck_name, key)
        if key not in dict.keys(self):
            _record("w", self._aiocheck_name, key)
        return super().setdefault(key, default)

    def update(self, *args, **kwargs):
        other = dict(*args, **kwargs)
        for key in other:
            _record("w", self._aiocheck_name, key)
        super().update(other)

    def clear(self):
        for key in list(dict.keys(self)):
            _record("w", self._aiocheck_name, key)
        super().clear()


def track(name: str, mapping: Optional[MutableMapping] = None) -> MutableMapping:
    """Wrap ``mapping`` in a TrackedDict when the probe is enabled; return
    it unchanged (or a fresh plain dict) otherwise."""
    if mapping is None:
        mapping = {}
    if not enabled():
        return mapping
    return TrackedDict(name, mapping)


@dataclass
class Conflict:
    kind: str  # "read-await-write" | "write-write"
    dict_name: str
    key: Any
    task: str  # the task whose write is hazardous
    other_task: str  # the task it raced with
    read_seq: Optional[int]
    write_seq: int
    other_seq: int

    def __str__(self) -> str:
        if self.kind == "read-await-write":
            return (
                f"read-await-write on {self.dict_name}[{self.key!r}]: "
                f"{self.task} read at #{self.read_seq}, {self.other_task} "
                f"wrote at #{self.other_seq}, {self.task} wrote back at "
                f"#{self.write_seq} (stale view)"
            )
        return (
            f"write-write on {self.dict_name}[{self.key!r}]: {self.other_task} "
            f"wrote at #{self.other_seq}, then {self.task} overwrote at "
            f"#{self.write_seq} without reading it"
        )


def reset() -> None:
    _events.clear()


def events() -> List[Tuple[int, str, str, str, Any]]:
    return list(_events)


def conflicts() -> List[Conflict]:
    """Analyze the recorded trace for cross-task hazards."""
    out: List[Conflict] = []
    # Per (dict, key): ordered history of (seq, task, op).
    history: Dict[Tuple[str, Any], List[Tuple[int, str, str]]] = {}
    for seq, task, op, name, key in _events:
        history.setdefault((name, key), []).append((seq, task, op))
    for (name, key), ops in history.items():
        for i, (seq, task, op) in enumerate(ops):
            if op != "w" or task == "<no-task>":
                continue
            # Last op by this task before this write.
            last_read = None
            last_own_write = None
            for pseq, ptask, pop in reversed(ops[:i]):
                if ptask == task:
                    if pop == "r" and last_read is None:
                        last_read = pseq
                    if pop == "w":
                        last_own_write = pseq
                    break_after = last_read is not None or last_own_write is not None
                    if break_after:
                        break
            if last_read is not None:
                # Foreign write between our read and our write?
                for pseq, ptask, pop in ops[:i]:
                    if (
                        pop == "w"
                        and ptask not in (task, "<no-task>")
                        and last_read < pseq < seq
                    ):
                        out.append(
                            Conflict(
                                "read-await-write",
                                name,
                                key,
                                task,
                                ptask,
                                last_read,
                                seq,
                                pseq,
                            )
                        )
                        break
            else:
                # No prior read by this task: write-write if the immediately
                # preceding write came from another task.
                for pseq, ptask, pop in reversed(ops[:i]):
                    if pop != "w":
                        continue
                    if ptask not in (task, "<no-task>"):
                        out.append(
                            Conflict(
                                "write-write",
                                name,
                                key,
                                task,
                                ptask,
                                None,
                                seq,
                                pseq,
                            )
                        )
                    break
    out.sort(key=lambda c: c.write_seq)
    return out


def report() -> str:
    cs = conflicts()
    if not cs:
        return "aiocheck: no cross-task conflicts observed"
    lines = [f"aiocheck: {len(cs)} cross-task conflict(s) observed"]
    lines.extend(f"  {c}" for c in cs)
    return "\n".join(lines)
