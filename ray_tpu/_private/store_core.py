"""Object-store bookkeeping core: native C++ engine with pure-Python fallback.

The native path (ray_tpu._native._store, src/store_core.cc) implements the
plasma-style arena allocator + object lifecycle + LRU eviction in C++; this
module provides an API-identical Python implementation for pure-python
installs and selects between them.

API (both implementations):
    alloc(oid, size, pin=True) -> offset | -1
    seal/touch/pin/unpin(oid), free(oid) -> size
    evict(nbytes, grace_ticks=0) -> [oid]
    lookup(oid) -> (offset, size, sealed, pinned) | None
    contains(oid) -> bool (sealed)
    used / capacity / num_objects, fragmentation() -> (ratio, largest, spans)
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

try:
    from ray_tpu._native._store import StoreCore as NativeStoreCore

    NATIVE = True
except ImportError:  # pragma: no cover - pure-python installs
    NativeStoreCore = None
    NATIVE = False


def _round(size: int) -> int:
    return (max(1, size) + 63) & ~63


class PyStoreCore:
    """Pure-Python mirror of the C++ StoreCore."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.used = 0
        self._tick = 0
        # free spans: offset -> size, sorted offset list (coalescing lookups),
        # and (size, offset) sorted list (best-fit)
        self._by_offset: Dict[int, int] = {0: capacity}
        self._offsets: List[int] = [0]
        self._by_size: List[Tuple[int, int]] = [(capacity, 0)]
        # oid -> [offset, size, sealed, pinned, tick]
        self._objects: Dict[str, list] = {}
        self._lru: Dict[int, str] = {}

    @property
    def num_objects(self) -> int:
        return len(self._objects)

    def _touch(self, oid: str, entry: list) -> None:
        self._lru.pop(entry[4], None)
        self._tick += 1
        entry[4] = self._tick
        self._lru[self._tick] = oid

    def alloc(self, oid: str, size: int, pin: bool = True) -> int:
        if oid in self._objects:
            raise KeyError(f"object {oid} already allocated")
        rsize = _round(size)
        i = bisect.bisect_left(self._by_size, (rsize, 0))
        if i >= len(self._by_size):
            return -1
        span_size, span_off = self._by_size.pop(i)
        del self._by_offset[span_off]
        self._offsets.pop(bisect.bisect_left(self._offsets, span_off))
        if span_size > rsize:
            rest = (span_off + rsize, span_size - rsize)
            self._by_offset[rest[0]] = rest[1]
            bisect.insort(self._offsets, rest[0])
            bisect.insort(self._by_size, (rest[1], rest[0]))
        entry = [span_off, size, False, bool(pin), 0]
        self._objects[oid] = entry
        self._touch(oid, entry)
        self.used += size
        return span_off

    def _drop_span(self, off: int, size: int) -> None:
        del self._by_offset[off]
        self._offsets.pop(bisect.bisect_left(self._offsets, off))
        self._by_size.pop(bisect.bisect_left(self._by_size, (size, off)))

    def _free_span(self, offset: int, size: int) -> None:
        size = _round(size)
        # Coalesce with successor span, found by exact offset.
        nxt = self._by_offset.get(offset + size)
        if nxt is not None:
            self._drop_span(offset + size, nxt)
            size += nxt
        # Coalesce with predecessor, found via the sorted offset index.
        i = bisect.bisect_left(self._offsets, offset)
        if i > 0:
            prev_off = self._offsets[i - 1]
            prev_size = self._by_offset[prev_off]
            if prev_off + prev_size == offset:
                self._drop_span(prev_off, prev_size)
                offset, size = prev_off, prev_size + size
        self._by_offset[offset] = size
        bisect.insort(self._offsets, offset)
        bisect.insort(self._by_size, (size, offset))

    def seal(self, oid: str) -> None:
        e = self._objects[oid]
        e[2] = True
        self._touch(oid, e)

    def touch(self, oid: str) -> None:
        e = self._objects.get(oid)
        if e is not None:
            self._touch(oid, e)

    def pin(self, oid: str) -> None:
        e = self._objects.get(oid)
        if e is not None:
            e[3] = True

    def unpin(self, oid: str) -> None:
        e = self._objects.get(oid)
        if e is not None:
            e[3] = False

    def free(self, oid: str) -> int:
        e = self._objects.pop(oid, None)
        if e is None:
            return 0
        self._free_span(e[0], e[1])
        self._lru.pop(e[4], None)
        self.used -= e[1]
        return e[1]

    def evict(self, nbytes: int, grace_ticks: int = 0) -> List[str]:
        out: List[str] = []
        freed = 0
        limit = self._tick - grace_ticks if grace_ticks else None
        for tick in sorted(self._lru):
            if freed >= nbytes:
                break
            if limit is not None and tick > limit:
                break
            oid = self._lru[tick]
            e = self._objects.get(oid)
            if e is None or not e[2] or e[3]:
                continue
            freed += e[1]
            self.free(oid)
            out.append(oid)
        return out

    def lookup(self, oid: str) -> Optional[Tuple[int, int, bool, bool]]:
        e = self._objects.get(oid)
        if e is None:
            return None
        return (e[0], e[1], e[2], e[3])

    def contains(self, oid: str) -> bool:
        e = self._objects.get(oid)
        return e is not None and e[2]

    def fragmentation(self) -> Tuple[float, int, int]:
        free_total = self.capacity - self.used
        largest = self._by_size[-1][0] if self._by_size else 0
        frag = 0.0 if free_total == 0 else 1.0 - largest / free_total
        return (frag, largest, len(self._by_offset))


def make_store_core(capacity: int):
    if NativeStoreCore is not None:
        return NativeStoreCore(capacity)
    return PyStoreCore(capacity)
