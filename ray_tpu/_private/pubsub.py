"""GCS-side pubsub publisher with per-subscriber bounded queues.

Analog of src/ray/pubsub/publisher.h: each subscriber connection gets its own
bounded message queue drained by its own sender task with transport-level
backpressure (``conn.drain()``). A slow or wedged subscriber therefore never
blocks the publisher's event loop or other subscribers; once its queue fills,
its OLDEST messages drop (counted) — matching the reference's
``publisher_entity_buffer`` overflow policy of shedding the backlog rather
than the publisher.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Any, Dict

from ray_tpu._private import rpc, telemetry
from ray_tpu._private.common import config

logger = logging.getLogger(__name__)

_TEL_PUBLISHED = telemetry.counter(
    "gcs", "pubsub_published", "messages published to the GCS pubsub"
)
_TEL_FANOUT = telemetry.counter(
    "gcs", "pubsub_fanout", "per-subscriber deliveries enqueued (fan-out)"
)
_TEL_DROPPED = telemetry.counter(
    "gcs", "pubsub_dropped", "messages shed from slow subscribers' queues"
)


class _SubscriberState:
    __slots__ = ("conn", "queue", "draining", "dropped")

    def __init__(self, conn: rpc.Connection, maxlen: int):
        self.conn = conn
        self.queue: deque = deque(maxlen=maxlen)
        self.draining = False
        self.dropped = 0


class Publisher:
    def __init__(self) -> None:
        # channel -> {conn id -> state}
        self.channels: Dict[str, Dict[int, _SubscriberState]] = {}
        self.total_dropped = 0

    def subscribe(self, channel: str, conn: rpc.Connection) -> None:
        self.channels.setdefault(channel, {})[id(conn)] = _SubscriberState(
            conn, max(1, config.pubsub_max_buffered_msgs)
        )

    def remove_subscriber(self, conn: rpc.Connection) -> None:
        cid = id(conn)
        for subs in self.channels.values():
            subs.pop(cid, None)

    def unsubscribe(self, channel: str, conn: rpc.Connection) -> None:
        subs = self.channels.get(channel)
        if subs is None:
            return
        subs.pop(id(conn), None)
        if not subs:
            del self.channels[channel]

    def publish(self, channel: str, msg: Any) -> None:
        """Enqueue to every subscriber; returns immediately (never blocks the
        caller on a slow subscriber's socket)."""
        _TEL_PUBLISHED.inc()
        subs = self.channels.get(channel)
        if not subs:
            return
        frame = {"channel": channel, "msg": msg}
        # Pack once, write the same bytes to every subscriber (None while a
        # chaos interceptor is installed -> per-subscriber packing below).
        packed = rpc.pack_push("Pub", frame)
        item = frame if packed is None else packed
        for state in list(subs.values()):
            if state.conn.closed:
                subs.pop(id(state.conn), None)
                continue
            _TEL_FANOUT.inc()
            if len(state.queue) == state.queue.maxlen:
                state.dropped += 1
                self.total_dropped += 1
                _TEL_DROPPED.inc()
                if state.dropped in (1, 100, 10000):
                    logger.warning(
                        "pubsub subscriber %s slow on %r: %d messages dropped",
                        state.conn.peername,
                        channel,
                        state.dropped,
                    )
            state.queue.append(item)
            if not state.draining:
                state.draining = True
                rpc.spawn(self._drain(state))

    async def _drain(self, state: _SubscriberState) -> None:
        try:
            while state.queue:
                item = state.queue.popleft()
                try:
                    if isinstance(item, bytes):
                        state.conn.push_packed_nowait(item)
                    else:
                        state.conn.push_nowait("Pub", item)
                    # Backpressure on THIS subscriber's transport only.
                    await state.conn.drain()
                except (rpc.ConnectionLost, rpc.RpcError):
                    self.remove_subscriber(state.conn)
                    return
        finally:
            state.draining = False
            # Re-check: a publish may have raced the finally.
            if state.queue and not state.conn.closed:
                state.draining = True
                rpc.spawn(self._drain(state))

    def stats(self) -> dict:
        return {
            "channels": {
                ch: {
                    "subscribers": len(subs),
                    "queued": sum(len(s.queue) for s in subs.values()),
                    "dropped": sum(s.dropped for s in subs.values()),
                }
                for ch, subs in self.channels.items()
            },
            "total_dropped": self.total_dropped,
        }
