"""GCS-side pubsub publisher with per-subscriber bounded queues.

Analog of src/ray/pubsub/publisher.h: each subscriber connection gets its own
bounded message queue drained by its own sender task with transport-level
backpressure (``conn.drain()``). A slow or wedged subscriber therefore never
blocks the publisher's event loop or other subscribers; once its queue fills,
its OLDEST messages drop (counted) — matching the reference's
``publisher_entity_buffer`` overflow policy of shedding the backlog rather
than the publisher.

Two storm-hardening layers on top (docs/fault_tolerance.md "Resubscribe
protocol"):

- **Per-channel monotonic seqnos.** Every publish stamps the channel's next
  seqno; ``subscribe`` reports the channel's current seqno. A client that
  sees a seq jump (its queue overflowed here, or it missed publishes while
  disconnected) KNOWS it lost messages and pulls a channel snapshot
  (``Snapshot`` RPC) instead of acting on a stale picture — the general
  form of the one-shot GetActor the serve controller used to do by hand.
- **Per-tick batched fan-out.** Publishes from one event-loop tick coalesce
  into one ``PubBatch`` frame per channel, packed once and enqueued to
  every subscriber — a registration wave that publishes N membership events
  to M subscribers costs O(M) frames per tick, not O(N*M).
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Any, Dict, List, Tuple

from ray_tpu._private import rpc, telemetry
from ray_tpu._private.common import config

logger = logging.getLogger(__name__)

_TEL_PUBLISHED = telemetry.counter(
    "gcs", "pubsub_published", "messages published to the GCS pubsub"
)
_TEL_FANOUT = telemetry.counter(
    "gcs", "pubsub_fanout", "per-subscriber deliveries enqueued (fan-out)"
)
_TEL_DROPPED = telemetry.counter(
    "gcs", "pubsub_dropped", "messages shed from slow subscribers' queues"
)


class _SubscriberState:
    __slots__ = ("conn", "queue", "queued_msgs", "max_msgs", "draining", "dropped")

    def __init__(self, conn: rpc.Connection, max_msgs: int):
        self.conn = conn
        # Entries are (frame, n_messages): the bound is on MESSAGES, not
        # frames, so per-tick batching can't inflate a slow subscriber's
        # backlog past the same budget the unbatched path had.
        self.queue: deque = deque()
        self.queued_msgs = 0
        self.max_msgs = max_msgs
        self.draining = False
        self.dropped = 0


class Publisher:
    def __init__(self) -> None:
        # Instance identity: seqnos restart from 0 with a fresh Publisher
        # (GCS restart), so subscribers must not compare seqs across
        # publisher lifetimes. The epoch rides Subscribe/Snapshot replies;
        # an epoch change tells the client "your last-seen seq means
        # nothing — resync".
        import uuid

        self.epoch = uuid.uuid4().hex[:12]
        # channel -> {conn id -> state}
        self.channels: Dict[str, Dict[int, _SubscriberState]] = {}
        # channel -> last published seqno (monotonic from 1; advances even
        # with no subscribers so a later subscriber's baseline is honest).
        self.seqnos: Dict[str, int] = {}
        self.total_dropped = 0
        # Publishes buffered for the current loop tick (channel, msg, seq).
        self._pending: List[Tuple[str, Any, int]] = []
        self._flush_scheduled = False

    def subscribe(self, channel: str, conn: rpc.Connection) -> int:
        """Attach; returns the channel's current seqno — the subscriber's
        gap-detection baseline (everything at or before it predates the
        subscription)."""
        self.channels.setdefault(channel, {})[id(conn)] = _SubscriberState(
            conn, max(1, config.pubsub_max_buffered_msgs)
        )
        return self.seqnos.get(channel, 0)

    def remove_subscriber(self, conn: rpc.Connection) -> None:
        cid = id(conn)
        for subs in self.channels.values():
            subs.pop(cid, None)

    def unsubscribe(self, channel: str, conn: rpc.Connection) -> None:
        subs = self.channels.get(channel)
        if subs is None:
            return
        subs.pop(id(conn), None)
        if not subs:
            del self.channels[channel]

    def publish(self, channel: str, msg: Any) -> None:
        """Stamp the channel seqno and buffer for the per-tick flush;
        returns immediately (never blocks the caller on a slow
        subscriber's socket)."""
        _TEL_PUBLISHED.inc()
        seq = self.seqnos.get(channel, 0) + 1
        self.seqnos[channel] = seq
        self._pending.append((channel, msg, seq))
        if self._flush_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self.flush()  # no loop (tests): deliver inline
            return
        self._flush_scheduled = True
        loop.call_soon(self.flush)

    def flush(self) -> None:
        """Fan the tick's buffered publishes out: the tick's publishes on
        one channel coalesce into PubBatch frames, each packed once and
        enqueued to every subscriber. Frames are chunked below a
        subscriber's whole message budget so the oldest-first eviction in
        ``_enqueue`` stays meaningful."""
        self._flush_scheduled = False
        pending, self._pending = self._pending, []
        if not pending:
            return
        by_channel: Dict[str, List[list]] = {}
        for channel, msg, seq in pending:
            by_channel.setdefault(channel, []).append([channel, msg, seq])
        chunk = max(1, min(256, config.pubsub_max_buffered_msgs))
        for channel, items in by_channel.items():
            subs = self.channels.get(channel)
            if not subs:
                continue
            for start in range(0, len(items), chunk):
                part = items[start : start + chunk]
                frame = {"items": part}
                # Pack once, write the same bytes to every subscriber (None
                # while a chaos interceptor is installed -> per-subscriber
                # packing in _drain).
                packed = rpc.pack_push("PubBatch", frame)
                item = frame if packed is None else packed
                for state in list(subs.values()):
                    if state.conn.closed:
                        subs.pop(id(state.conn), None)
                        continue
                    self._enqueue(state, channel, item, len(part))

    def _enqueue(self, state: _SubscriberState, channel: str, item, n: int) -> None:
        _TEL_FANOUT.inc(n)
        evicted = 0
        while state.queue and state.queued_msgs + n > state.max_msgs:
            _, dn = state.queue.popleft()
            state.queued_msgs -= dn
            evicted += dn
        if evicted:
            state.dropped += evicted
            self.total_dropped += evicted
            _TEL_DROPPED.inc(evicted)
            if state.dropped == evicted or (
                state.dropped // 1000 != (state.dropped - evicted) // 1000
            ):
                logger.warning(
                    "pubsub subscriber %s slow on %r: %d messages dropped"
                    " (seq gap will trigger a snapshot pull)",
                    state.conn.peername,
                    channel,
                    state.dropped,
                )
        state.queue.append((item, n))
        state.queued_msgs += n
        if state.draining:
            return
        # Fan-out fast path: with no backlog and a writable transport, write
        # inline — no drain task per subscriber per tick (at N subscribers
        # that is N task creations per broadcast round, the dominant cost of
        # a view-head flush on a large cluster). A paused transport or a
        # queue that built up behind one falls back to the drain task, which
        # awaits conn.drain() between writes — backpressure semantics (a
        # slow subscriber sheds its OWN backlog, stalls nobody) unchanged.
        if len(state.queue) == 1 and not state.conn.write_paused:
            item, n = state.queue.popleft()
            state.queued_msgs -= n
            try:
                if isinstance(item, bytes):
                    state.conn.push_packed_now(item)
                else:
                    state.conn.push_nowait("PubBatch", item)
            except (rpc.ConnectionLost, rpc.RpcError):
                self.remove_subscriber(state.conn)
            return
        state.draining = True
        rpc.spawn(self._drain(state))

    async def _drain(self, state: _SubscriberState) -> None:
        try:
            while state.queue:
                item, n = state.queue.popleft()
                state.queued_msgs -= n
                try:
                    if isinstance(item, bytes):
                        state.conn.push_packed_nowait(item)
                    else:
                        state.conn.push_nowait("PubBatch", item)
                    # Backpressure on THIS subscriber's transport only.
                    await state.conn.drain()
                except (rpc.ConnectionLost, rpc.RpcError):
                    self.remove_subscriber(state.conn)
                    return
        finally:
            state.draining = False
            # Re-check: a publish may have raced the finally.
            if state.queue and not state.conn.closed:
                state.draining = True
                rpc.spawn(self._drain(state))

    def stats(self) -> dict:
        return {
            "channels": {
                ch: {
                    "subscribers": len(subs),
                    "queued": sum(s.queued_msgs for s in subs.values()),
                    "dropped": sum(s.dropped for s in subs.values()),
                }
                for ch, subs in self.channels.items()
            },
            "total_dropped": self.total_dropped,
        }
