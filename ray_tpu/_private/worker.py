"""The global Worker singleton and the sync<->async bridge.

Analog of the reference's python/ray/_private/worker.py: holds the process-wide
connection state (`global_worker`), implements init/shutdown and the public
get/put/wait primitives by posting coroutines onto the runtime event loop.

In a driver, the loop runs on a dedicated background thread. In a worker
process, the loop is the main thread (worker_main) and user task code runs on
executor threads — either way, sync API calls bridge with
run_coroutine_threadsafe.
"""

from __future__ import annotations

import asyncio
import atexit
import concurrent.futures
import threading
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private import rpc
from ray_tpu._private.common import RayTpuError, config
from ray_tpu._private.core_worker import CoreWorker, ObjectRef
from ray_tpu._private.ids import JobID, WorkerID
from ray_tpu._private.node import Node


class Worker:
    def __init__(self):
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self.node: Optional[Node] = None
        self.core: Optional[CoreWorker] = None
        self.mode: str = "disconnected"
        self.namespace: str = "default"
        self._owns_loop = False
        # Client-mode context (remote driver via proxy); set by init("ray-tpu://...").
        self.client = None

    @property
    def connected(self) -> bool:
        return self.core is not None

    # -- event loop bridge ---------------------------------------------------

    def _start_loop(self) -> None:
        # Honor the rpc_event_loop knob (uvloop when installed; no-op on
        # the stock config) before the policy mints the driver's loop.
        rpc.install_event_loop()
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, name="ray_tpu_event_loop", daemon=True)
        t.start()
        started.wait()
        self.loop = loop
        self._loop_thread = t
        self._owns_loop = True

    def run_async(self, coro, timeout: Optional[float] = None):
        if self.loop is None:
            raise RayTpuError("ray_tpu not initialized; call ray_tpu.init()")
        if threading.current_thread() is self._loop_thread or (
            not self._owns_loop and self._on_loop_thread()
        ):
            raise RayTpuError(
                "sync API called from the event-loop thread; use the async API"
            )
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError as e:
            fut.cancel()
            from ray_tpu._private.common import GetTimeoutError

            raise GetTimeoutError("operation timed out") from e

    def _on_loop_thread(self) -> bool:
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False


global_worker = Worker()
_init_lock = threading.Lock()


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    worker_env: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Start (or connect to) a cluster and attach this process as driver.

    With no address, boots a head node in-process (GCS + raylet on a
    background event loop; reference: ray.init at worker.py:1214).
    `address="host:port"` connects to an existing GCS. `address="auto"` (or
    the RAY_TPU_ADDRESS env var, set for submitted jobs) finds the running
    cluster.
    """
    import os as _os

    config.refresh()  # pick up env overrides set after import (fixtures)

    if address and (address.startswith("ray-tpu://") or address.startswith("ray://")):
        # Client mode (reference: Ray Client, ray.init("ray://...")): drive
        # the cluster through its proxy endpoint; this process never joins
        # the cluster network.
        from ray_tpu.util import client as client_mod

        with _init_lock:
            w = global_worker
            if w.connected or w.mode == "client":
                if ignore_reinit_error:
                    return {"address": address}
                raise RayTpuError("ray_tpu.init() called twice")
            ctx = client_mod.connect(address, namespace=namespace)
            w.client = ctx
            w.mode = "client"
            atexit.register(shutdown)
            return {"address": address, "job_id": ctx.job_id}

    if address == "auto":
        address = _os.environ.get("RAY_TPU_ADDRESS") or _read_cluster_address()
        if address is None:
            raise RayTpuError("address='auto' but no running cluster found")
    elif address is None and _os.environ.get("RAY_TPU_ADDRESS"):
        address = _os.environ["RAY_TPU_ADDRESS"]
    with _init_lock:
        w = global_worker
        if w.connected:
            if ignore_reinit_error:
                return {"address": w.core.gcs.conn.peername}
            raise RayTpuError("ray_tpu.init() called twice")
        if w.loop is None:
            w._start_loop()
        if namespace:
            w.namespace = namespace

        async def _bring_up():
            node = None
            if address is None:
                node = Node(
                    head=True,
                    num_cpus=num_cpus,
                    num_tpus=num_tpus,
                    resources=resources,
                    object_store_memory=object_store_memory,
                    worker_env=worker_env,
                )
                await node.start()
                gcs_addr = node.gcs_addr
                raylet_addr = node.raylet_addr
            else:
                host, port = address.rsplit(":", 1)
                gcs_addr = (host, int(port))
                # Find a raylet: ask GCS for nodes, prefer a local one.
                conn = await rpc.connect(*gcs_addr)
                reply = await conn.call("GetAllNodes")
                await conn.close()
                alive = [n for n in reply["nodes"] if n["state"] == "ALIVE"]
                if not alive:
                    raise RayTpuError("no alive nodes in cluster")
                raylet_addr = tuple(alive[0]["addr"])

            server = rpc.Server("127.0.0.1", 0)
            addr = await server.start()
            raylet_conn = await rpc.connect(*raylet_addr, handlers=server._handlers)
            gcs_conn = await rpc.connect(*gcs_addr, handlers=server._handlers)
            job_id = JobID.from_random().hex()
            core = CoreWorker(
                job_id=job_id,
                session_name=node.session_name if node else "external",
                node_id="driver",
                gcs_conn=gcs_conn,
                raylet_conn=raylet_conn,
                is_driver=True,
                worker_id=WorkerID.from_random().hex(),
                server=server,
                gcs_leader_file=node.gcs_leader_file() if node else None,
            )
            core.addr = addr
            core.raylet_addr = tuple(raylet_addr)
            core.start_background()
            await core.gcs.call(
                "RegisterJob", {"job_id": job_id, "driver_addr": list(addr)}
            )
            if config.log_to_driver:
                await core.gcs.subscribe(
                    "logs", lambda msg: _print_worker_log(msg, job_id)
                )
            return node, core, gcs_addr

        node, core, gcs_addr = w.run_async(
            _bring_up(), timeout=config.driver_bringup_timeout_s
        )
        w.node = node
        w.core = core
        w.mode = "driver"
        atexit.register(shutdown)
        return {"address": f"{gcs_addr[0]}:{gcs_addr[1]}", "session": core.session_name}


def _print_worker_log(msg: dict, my_job_id: Optional[str] = None) -> None:
    """Echo a worker-log pubsub batch onto the driver's stderr (reference:
    log_to_driver via log_monitor.py -> print_to_stdstream). Prefix mirrors
    the reference's ``(pid=..., ip=...)`` tag. Batches attributed to another
    job are dropped; unattributed batches (pooled task workers) are echoed
    to every driver."""
    import sys as _sys

    batch_job = msg.get("job_id")
    if batch_job is not None and my_job_id is not None and batch_job != my_job_id:
        return
    tag = f"(pid={msg.get('pid')}, worker={str(msg.get('worker_id'))[:8]})"
    out = _sys.stderr
    for line in msg.get("lines") or []:
        print(f"{tag} {line}", file=out)


def cluster_state_file() -> str:
    """State file written by `ray-tpu start` (single source of the path)."""
    import os

    return os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu_cluster.json")


def _read_cluster_address() -> Optional[str]:
    """Address of a cluster started via `ray-tpu start` on this machine."""
    import json

    try:
        with open(cluster_state_file()) as f:
            return json.load(f)["address"]
    except Exception:
        return None


def attach_existing(core: CoreWorker, loop: asyncio.AbstractEventLoop) -> None:
    """Used by worker processes: the loop already exists (main thread)."""
    w = global_worker
    w.core = core
    w.loop = loop
    w.mode = "worker"
    w._owns_loop = False


def shutdown() -> None:
    w = global_worker
    if w.mode == "client":
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass
        ctx, w.client = w.client, None
        w.mode = "disconnected"
        if ctx is not None:
            ctx.disconnect()
        return
    if not w.connected:
        return
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass
    core, node = w.core, w.node
    w.core = None
    w.node = None
    w.mode = "disconnected"

    async def _down():
        try:
            if core is not None:
                try:
                    await asyncio.wait_for(
                        core.gcs.call("JobFinished", {"job_id": core.job_id}), 5
                    )
                except Exception:
                    pass
                await core.close()
        finally:
            if node is not None:
                await node.stop()

    try:
        w.run_async(_down(), timeout=config.driver_shutdown_timeout_s)
    except Exception:
        pass

    async def _cancel_remaining():
        tasks = [
            t
            for t in asyncio.all_tasks()
            if t is not asyncio.current_task() and not t.done()
        ]
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)

    # Only sweep the loop when we own it: in worker mode the loop is the
    # process's serving loop and its RPC/heartbeat tasks must keep running.
    if w._owns_loop:
        try:
            w.run_async(_cancel_remaining(), timeout=5)
        except Exception:
            pass
    if w._owns_loop and w.loop is not None:
        w.loop.call_soon_threadsafe(w.loop.stop)
        if w._loop_thread is not None:
            w._loop_thread.join(timeout=5)
        w.loop = None
        w._loop_thread = None
        w._owns_loop = False


def _core() -> CoreWorker:
    core = global_worker.core
    if core is None:
        raise RayTpuError("ray_tpu is not initialized; call ray_tpu.init() first")
    return core


# -- public primitives (sync) ------------------------------------------------


def put(value: Any) -> ObjectRef:
    if global_worker.mode == "client":
        return global_worker.client.put(value)
    return global_worker.run_async(_core().put(value))


def get(refs, timeout: Optional[float] = None):
    if global_worker.mode == "client":
        return global_worker.client.get(refs, timeout)
    single = isinstance(refs, ObjectRef)
    ref_list: List[ObjectRef] = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get expects ObjectRef(s), got {type(r)}")
    result = global_worker.run_async(
        _core().get_objects(ref_list, timeout),
        timeout=None if timeout is None else timeout + 30,
    )
    return result[0] if single else result


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
):
    ref_list = list(refs)
    if num_returns > len(ref_list):
        raise ValueError("num_returns exceeds number of refs")
    if global_worker.mode == "client":
        return global_worker.client.wait(
            ref_list, num_returns=num_returns, timeout=timeout
        )
    return global_worker.run_async(
        _core().wait(ref_list, num_returns, timeout),
        timeout=None if timeout is None else timeout + 30,
    )


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Best-effort cancellation of the task producing `ref` (reference:
    ray.cancel at worker.py:2932)."""
    if global_worker.mode == "client":
        global_worker.client.cancel(ref, force=force)
        return
    if not isinstance(ref, ObjectRef):
        raise TypeError("ray_tpu.cancel expects an ObjectRef")
    global_worker.run_async(_core().cancel(ref, force))


def kill(actor, *, no_restart: bool = True) -> None:
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill expects an ActorHandle")
    if global_worker.mode == "client":
        global_worker.client.kill(actor._actor_id, no_restart=no_restart)
        return
    global_worker.run_async(_core().kill_actor(actor._actor_id, no_restart))


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.actor import ActorHandle

    if global_worker.mode == "client":
        return global_worker.client.get_actor(name, namespace)
    reply = global_worker.run_async(
        _core().gcs.call(
            "GetNamedActor",
            {"name": name, "namespace": namespace or global_worker.namespace},
        )
    )
    info = reply["actor"]
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(info["actor_id"], info.get("max_task_retries", 0))


def nodes() -> List[dict]:
    if global_worker.mode == "client":
        return global_worker.client.nodes()
    return global_worker.run_async(_core().gcs.call("GetAllNodes"))["nodes"]


def cluster_resources() -> Dict[str, float]:
    from ray_tpu._private.common import ResourceSet

    total = ResourceSet()
    for n in nodes():
        if n["state"] == "ALIVE":
            total = total + ResourceSet.from_units(n["total"])
    return total.to_dict()


def available_resources() -> Dict[str, float]:
    from ray_tpu._private.common import ResourceSet

    total = ResourceSet()
    for n in nodes():
        if n["state"] == "ALIVE":
            total = total + ResourceSet.from_units(n["available"])
    return total.to_dict()


def is_initialized() -> bool:
    return global_worker.connected or global_worker.mode == "client"
