"""Prioritized, bandwidth-capped pull admission (reference:
src/ray/object_manager/pull_manager.h — pulls are queued by purpose
priority and admitted up to a bytes-in-flight quota, so a burst of bulk
task-argument transfers cannot starve an interactive ray.get, and a node
cannot buffer an unbounded number of concurrent inbound transfers).

Priorities (highest first), matching the reference's bundle priority:
    get      — a caller is blocked in ray.get right now (the driver/worker
               payload-resolution path, the default)
    wait     — ray.wait readiness probes (reserved: wait() currently
               checks readiness without pulling, so nothing produces this
               class yet)
    task_arg — a worker resolving a queued task's arguments
               (worker_main.load_args)
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import List, Tuple

from ray_tpu._private import telemetry

PRIORITY = {"get": 0, "wait": 1, "task_arg": 2}

_TEL_STALLED = telemetry.counter(
    "object", "pull_streams_stalled", "inbound chunk streams declared stalled"
)
_TEL_REREQUESTED = telemetry.counter(
    "object", "pull_streams_rerequested",
    "stalled chunk streams re-requested from the source",
)
_TEL_RESTORE_FALLBACKS = telemetry.counter(
    "object", "pull_restore_fallbacks",
    "pulls that recovered via owner-directed RestoreSpilled after the "
    "in-memory probe missed",
)


class PullStalled(Exception):
    """A chunk stream stopped making progress (source dropped mid-push or
    chunks were lost); the caller should abort the assembly and re-request."""


class PullManager:
    def __init__(
        self,
        max_bytes_in_flight: int,
        stall_timeout_s: float = 5.0,
        max_rerequests: int = 2,
    ):
        self.max_bytes = int(max_bytes_in_flight)
        self.bytes_in_flight = 0
        self.active = 0
        # Chunk-stream supervision: a push assembly with no byte progress
        # for stall_timeout_s is declared stalled; the pull path re-requests
        # the push up to max_rerequests times before falling back to the
        # request/reply chunk loop.
        self.stall_timeout_s = float(stall_timeout_s)
        self.max_rerequests = int(max_rerequests)
        self.stalled_streams = 0
        self.rerequested_streams = 0
        # Pulls that found no in-memory copy but recovered one via an
        # explicit RestoreSpilled to the holder (a spilled object is a valid
        # pull source — the restore fallback runs before object-lost).
        self.restore_fallbacks = 0
        # Heap of (priority, seq, size, future) — seq keeps FIFO order
        # within a priority class and makes heap entries comparable.
        self._waiters: List[Tuple[int, int, int, asyncio.Future]] = []
        self._seq = itertools.count()

    def _admissible(self, size: int) -> bool:
        # At least one transfer always runs: an object larger than the
        # whole quota must not deadlock (reference: the quota is soft for
        # the head-of-line pull).
        return self.active == 0 or self.bytes_in_flight + size <= self.max_bytes

    async def acquire(self, size: int, purpose: str = "get") -> None:
        """Wait for admission of a transfer of `size` bytes."""
        if not self._waiters and self._admissible(size):
            self.bytes_in_flight += size
            self.active += 1
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        heapq.heappush(
            self._waiters,
            (PRIORITY.get(purpose, 1), next(self._seq), size, fut),
        )
        # A higher-priority arrival may now BE the admissible head (e.g. a
        # small get behind a queued oversized task_arg): admit from the head
        # immediately rather than waiting for an unrelated release().
        self._drain()
        try:
            await fut
        except asyncio.CancelledError:
            # Cancellation can land *after* _drain() admitted this waiter
            # (future resolved, quota already charged) but before the task
            # resumed; the caller will never call release(), so undo the
            # admission here. A still-pending future means the quota was
            # never charged — _drain() skips cancelled entries.
            if not fut.cancelled():
                self.release(size)
            raise

    def _drain(self) -> None:
        # Admit from the head strictly in priority order (no bypass: a
        # small low-priority pull must not starve a large high-priority
        # one indefinitely).
        while self._waiters:
            prio, seq, size_w, fut = self._waiters[0]
            if fut.cancelled():
                heapq.heappop(self._waiters)
                continue
            if not self._admissible(size_w):
                break
            heapq.heappop(self._waiters)
            self.bytes_in_flight += size_w
            self.active += 1
            fut.set_result(None)

    def release(self, size: int) -> None:
        self.bytes_in_flight -= size
        self.active -= 1
        # No clamping: an underflow here means a double release (or a
        # release without a matching acquire) upstream, and clamping would
        # silently widen the quota. Fail loudly so chaos seeds catch it.
        assert self.bytes_in_flight >= 0 and self.active >= 0, (
            f"pull quota underflow: bytes_in_flight={self.bytes_in_flight} "
            f"active={self.active} after release({size})"
        )
        self._drain()

    async def watch_stream(self, progress, done, timeout: float) -> None:
        """Supervise one inbound chunk stream until ``done()`` is truthy.

        ``progress()`` returns an opaque monotone marker (bytes received);
        when it stops changing for ``stall_timeout_s`` — the source died
        mid-push, or one-way chunks were dropped so the tail never arrives —
        raise :class:`PullStalled` so the caller can abort the half-written
        assembly and re-request instead of blocking until the 60s assembly
        janitor. ``timeout`` bounds the whole wait (healthy streams included).
        """
        loop = asyncio.get_running_loop()
        last = progress()
        last_change = loop.time()
        deadline = last_change + timeout
        while not done():
            await asyncio.sleep(0.05)
            now = loop.time()
            cur = progress()
            if cur != last:
                last, last_change = cur, now
            elif now - last_change >= self.stall_timeout_s:
                self.stalled_streams += 1
                _TEL_STALLED.inc()
                raise PullStalled(
                    f"chunk stream stalled at {cur!r} for "
                    f"{now - last_change:.1f}s"
                )
            if now >= deadline:
                self.stalled_streams += 1
                _TEL_STALLED.inc()
                raise PullStalled(f"chunk stream incomplete after {timeout}s")

    def stats(self) -> dict:
        return {
            "bytes_in_flight": self.bytes_in_flight,
            "active_pulls": self.active,
            "queued_pulls": len(self._waiters),
            "stalled_streams": self.stalled_streams,
            "rerequested_streams": self.rerequested_streams,
            "restore_fallbacks": self.restore_fallbacks,
        }
