"""Structured event framework (reference: src/ray/util/event.cc +
python/ray/_private/event/event_logger.py).

Events are operational facts about the cluster — node joined, node died,
actor restarted — recorded two ways:
  - durably: one JSON line per event appended to
    <session>/logs/events/event_<SOURCE>.log (the reference's event file
    layout, consumable by log shippers);
  - queryably: a bounded in-memory ring served over the GCS ListEvents RPC
    and the state API's list_cluster_events().
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import time
import uuid
from typing import Any, Deque, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


class EventLogger:
    def __init__(self, session_name: str, source: str, ring_size: int = 2000):
        self.source = source
        self.dir = os.path.join(
            tempfile.gettempdir(), f"ray_tpu_{session_name}", "logs", "events"
        )
        self.path = os.path.join(self.dir, f"event_{source}.log")
        self.ring: Deque[Dict[str, Any]] = collections.deque(maxlen=ring_size)
        self._fh = None

    def emit(
        self,
        label: str,
        message: str,
        severity: str = "INFO",
        **custom_fields: Any,
    ) -> Dict[str, Any]:
        event = {
            "event_id": uuid.uuid4().hex,
            "timestamp": time.time(),
            "severity": severity if severity in SEVERITIES else "INFO",
            "label": label,
            "message": message,
            "source_type": self.source,
            "source_pid": os.getpid(),
            "custom_fields": custom_fields,
        }
        self.ring.append(event)
        try:
            if self._fh is None:
                os.makedirs(self.dir, exist_ok=True)
                self._fh = open(self.path, "a", buffering=1)
            self._fh.write(json.dumps(event) + "\n")
        except OSError:
            pass  # events must never take the control plane down
        return event

    def list(
        self,
        severity: Optional[str] = None,
        label: Optional[str] = None,
        limit: int = 1000,
    ) -> List[Dict[str, Any]]:
        out = []
        for e in reversed(self.ring):
            if severity and e["severity"] != severity:
                continue
            if label and e["label"] != label:
                continue
            out.append(e)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def read_event_log(session_name: str, source: str) -> List[Dict[str, Any]]:
    """Parse a session's durable event file (what a log shipper would see)."""
    path = os.path.join(
        tempfile.gettempdir(),
        f"ray_tpu_{session_name}", "logs", "events", f"event_{source}.log",
    )
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    except OSError:
        pass
    return events
