"""CoreWorker: the in-process runtime embedded in every driver and worker.

TPU-native analog of the reference's CoreWorker (src/ray/core_worker/core_worker.h:292):
Put/Get/Wait, task submission over leased workers (direct task transport —
transport/direct_task_transport.h:75), direct actor submission with per-handle
sequence numbers (transport/sequential_actor_submit_queue.cc), ownership-based
reference counting (reference_count.cc), task retries (task_manager.cc), and an
object server so borrowers can pull owner-local objects.

Everything here is async and runs on the process's event loop; the public sync
API (ray_tpu/_private/worker.py) bridges via run_coroutine_threadsafe.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import rpc, serialization
from ray_tpu._private.common import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    ResourceSet,
    TaskCancelledError,
    TaskError,
    TaskSpec,
    WorkerCrashedError,
    config,
)
from ray_tpu._private.gcs import GcsClient
from ray_tpu._private.ids import ActorID, ObjectID, TaskID, deterministic_object_id
from ray_tpu._private.object_store import IN_PLASMA, INLINE, MemoryStore, PlasmaClient

logger = logging.getLogger(__name__)


class ObjectRefGenerator:
    """Value of a num_returns="dynamic" task: an iterable of ObjectRefs
    (reference: ray._raylet.ObjectRefGenerator / DynamicObjectRefGenerator)."""

    def __init__(self, refs):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]

    def __reduce__(self):
        return (ObjectRefGenerator, (self._refs,))


class ObjectRef:
    """A reference to a (possibly not-yet-computed) object.

    Carries the owner's object-server address so any holder can resolve the
    value (ownership model: the owner worker is the object's directory).
    """

    __slots__ = ("_hex", "_owner_addr", "_core", "__weakref__")

    def __init__(self, hex_id: str, owner_addr: Tuple[str, int], core: Optional["CoreWorker"] = None):
        self._hex = hex_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._core = core
        if core is not None:
            core.reference_table.add_local(hex_id)

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    @property
    def owner_addr(self):
        return self._owner_addr

    def __repr__(self):
        return f"ObjectRef({self._hex})"

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._hex == self._hex

    def __reduce__(self):
        serialization.record_contained_ref(self)
        deserializer = serialization.get_ref_deserializer()
        if deserializer is not None:
            return (deserializer, (self._hex, self._owner_addr))
        return (_plain_ref, (self._hex, self._owner_addr))

    def __del__(self):
        core = self._core
        if core is not None and not core.closed:
            try:
                core.reference_table.remove_local(self._hex, core)
            except Exception:
                pass

    def __await__(self):
        # Allows `await ref` inside async actors.
        core = self._core
        if core is None:
            raise RuntimeError("ObjectRef is not attached to a core worker")
        return core.get_objects([self], timeout=None).__await__()


def _plain_ref(hex_id, owner_addr):
    # Deserialized outside any worker context (e.g. in a subprocess tool):
    # ref without a core; get() requires re-attachment.
    return ObjectRef(hex_id, owner_addr, None)


class RefEntry:
    __slots__ = ("local", "submitted", "owned", "freed")

    def __init__(self):
        self.local = 0
        self.submitted = 0
        self.owned = False
        self.freed = False


class ReferenceTable:
    """Per-process reference counts driving object lifetime.

    Owner frees the object (memory store entry + shm primary copy) once the
    local python refcount and in-flight-task count both reach zero.
    Reference: src/ray/core_worker/reference_count.cc (we implement the
    owner-side protocol; cross-worker borrow counts are conservatively
    approximated by the submitted-task count).

    Thread-safe: mutated both from the event loop and from user threads
    (ObjectRef ctor/__del__, the synchronous submission fast path).
    """

    def __init__(self):
        import threading

        self.entries: Dict[str, RefEntry] = {}
        self._lock = threading.Lock()

    def _entry(self, oid: str) -> RefEntry:
        e = self.entries.get(oid)
        if e is None:
            e = self.entries[oid] = RefEntry()
        return e

    def add_local(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).local += 1

    def mark_owned(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).owned = True

    def add_submitted(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).submitted += 1

    def remove_submitted(self, oid: str, core: "CoreWorker") -> None:
        with self._lock:
            e = self.entries.get(oid)
            if e is None:
                return
            e.submitted -= 1
            self._maybe_free(oid, e, core)

    def remove_local(self, oid: str, core: "CoreWorker") -> None:
        with self._lock:
            e = self.entries.get(oid)
            if e is None:
                return
            e.local -= 1
            self._maybe_free(oid, e, core)

    def _maybe_free(self, oid: str, e: RefEntry, core: "CoreWorker") -> None:
        # Called with the lock held; the schedule_* sinks are plain appends.
        if e.local <= 0 and e.submitted <= 0 and not e.freed:
            e.freed = True
            del self.entries[oid]
            if e.owned:
                core.schedule_free(oid)
            # Drop this process's plasma hold: with no local refs left, user
            # code keeping a zero-copy view alive past this point is outside
            # the supported contract (same as the reference's buffer release).
            if oid in core.plasma.held:
                core.schedule_release(oid)


class Lease:
    def __init__(self, lease_id: str, worker_id: str, addr, conn, raylet_conn):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.addr = tuple(addr)
        self.conn: rpc.Connection = conn
        self.raylet_conn: rpc.Connection = raylet_conn


class _ShapePool:
    """Per-resource-shape lease state: idle leases, waiters, in-flight
    requests to the raylet."""

    __slots__ = ("idle", "waiters", "inflight")

    def __init__(self):
        self.idle: List[Lease] = []
        self.waiters: "asyncio.Queue[asyncio.Future]" = None  # lazily created
        self.inflight = 0


class LeasePool:
    """Granted-lease cache with pipelined acquisition and cancellation.

    Reference design: CoreWorkerDirectTaskSubmitter pipelines one lease
    request per queued task, reuses returned workers for queued tasks of the
    same shape, and cancels now-surplus raylet requests — without the
    cancellation, recycled leases starve the raylet's queue (resources are
    never returned while requests wait on them).
    """

    # Idle leases kept per shape before returning workers to the raylet.
    MAX_IDLE = 2

    def __init__(self, core: "CoreWorker"):
        self.core = core
        self.pools: Dict[tuple, _ShapePool] = {}
        self.waiters: Dict[tuple, List[asyncio.Future]] = {}

    @staticmethod
    def shape_key(resources: Dict[str, int], pg_id, bundle_index) -> tuple:
        return (tuple(sorted((resources or {}).items())), pg_id, bundle_index)

    def _pool(self, key) -> _ShapePool:
        p = self.pools.get(key)
        if p is None:
            p = self.pools[key] = _ShapePool()
        return p

    async def acquire(self, resources: Dict[str, int], pg_id=None, bundle_index=None) -> Lease:
        key = self.shape_key(resources, pg_id, bundle_index)
        pool = self._pool(key)
        while pool.idle:
            lease = pool.idle.pop()
            if not lease.conn.closed:
                return lease
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.setdefault(key, []).append(fut)
        pool.inflight += 1
        rpc.spawn(self._request_lease(key, resources, pg_id, bundle_index))
        return await fut

    async def _request_lease(self, key, resources, pg_id, bundle_index) -> None:
        from ray_tpu._private.ids import TaskID as _T

        pool = self._pool(key)
        lease_id = _T.from_random().hex()
        raylet_conn = self.core.raylet_conn
        try:
            hops = 0
            while True:
                reply = await raylet_conn.call(
                    "RequestWorkerLease",
                    {
                        "lease_id": lease_id,
                        "resources": resources,
                        "pg_id": pg_id,
                        "bundle_index": bundle_index,
                    },
                    timeout=None,
                )
                if reply.get("cancelled"):
                    return
                if reply.get("granted"):
                    conn = await self.core.connect_to(tuple(reply["worker_addr"]))
                    lease = Lease(
                        reply["lease_id"],
                        reply["worker_id"],
                        reply["worker_addr"],
                        conn,
                        raylet_conn,
                    )
                    self._dispatch(key, lease)
                    return
                spill = reply.get("spillback")
                if spill is None:
                    raise rpc.RpcError(
                        f"no node can host resources {resources} (cluster infeasible)"
                    )
                hops += 1
                if hops > 4:
                    raise rpc.RpcError("lease spillback loop exceeded 4 hops")
                raylet_conn = await self.core.connect_to(tuple(spill["addr"]))
        except Exception as e:
            # Fail one waiter (the request served one logical slot).
            waiters = self.waiters.get(key, [])
            while waiters:
                fut = waiters.pop(0)
                if not fut.done():
                    fut.set_exception(e)
                    break
        finally:
            pool.inflight -= 1

    def _dispatch(self, key, lease: Lease) -> None:
        waiters = self.waiters.get(key, [])
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result(lease)
                return
        pool = self._pool(key)
        if len(pool.idle) < self.MAX_IDLE:
            pool.idle.append(lease)
        else:
            rpc.spawn(self._return_worker(lease, dirty=False))

    async def release(self, lease: Lease, resources, pg_id=None, bundle_index=None, dirty=False):
        key = self.shape_key(resources, pg_id, bundle_index)
        pool = self._pool(key)
        if dirty or lease.conn.closed:
            await self._return_worker(lease, dirty=True)
            return
        # Serve a queued waiter directly and cancel one surplus in-flight
        # raylet request so the raylet's queue drains.
        waiters = self.waiters.get(key, [])
        handed = False
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result(lease)
                handed = True
                break
        if handed:
            return
        if len(pool.idle) < self.MAX_IDLE and pool.inflight == 0:
            pool.idle.append(lease)
        else:
            await self._return_worker(lease, dirty=False)

    async def _return_worker(self, lease: Lease, dirty: bool) -> None:
        try:
            await lease.raylet_conn.call(
                "ReturnWorker", {"lease_id": lease.lease_id, "dirty": dirty}
            )
        except rpc.RpcError:
            pass

    async def drain(self):
        for pool in self.pools.values():
            for lease in pool.idle:
                await self._return_worker(lease, dirty=False)
            pool.idle.clear()


class ActorSubmitter:
    """Direct transport to one actor with per-handle sequencing and
    restart-aware redirection."""

    def __init__(self, core: "CoreWorker", actor_id: str):
        self.core = core
        self.actor_id = actor_id
        self.seq = 0
        self.conn: Optional[rpc.Connection] = None
        self.state = "PENDING"
        self.addr = None
        self.incarnation = 0
        self._lock = asyncio.Lock()

    async def _resolve(self, timeout: float = 300.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply = await self.core.gcs.call("GetActor", {"actor_id": self.actor_id})
            info = reply["actor"]
            if info is None:
                raise ActorDiedError(f"actor {self.actor_id[:8]} unknown to GCS")
            self.state = info["state"]
            if info["state"] == "ALIVE":
                # A restarted incarnation starts its sequence log fresh.
                if info["num_restarts"] != self.incarnation:
                    self.incarnation = info["num_restarts"]
                    self.seq = 0
                self.addr = tuple(info["addr"])
                self.conn = await self.core.connect_to(self.addr)
                return
            if info["state"] == "DEAD":
                raise ActorDiedError(
                    f"actor {self.actor_id[:8]} is dead: {info.get('death_cause')}"
                )
            await asyncio.sleep(0.1)
        raise ActorDiedError(f"timed out waiting for actor {self.actor_id[:8]} to start")

    async def submit(self, spec: TaskSpec) -> dict:
        async with self._lock:
            if self.conn is None or self.conn.closed:
                self.conn = None
                await self._resolve()
            conn = self.conn
            spec.seq_no = self.seq
            self.seq += 1
        try:
            return await conn.call("PushActorTask", {"spec": spec.to_wire()})
        except rpc.ConnectionLost:
            # Actor worker died mid-call. In-flight tasks fail (reference
            # semantics: no silent at-least-once resend); the next submit
            # re-resolves and lands on the restarted incarnation if any.
            self.conn = None
            from ray_tpu._private.common import ActorUnavailableError

            raise ActorUnavailableError(
                f"actor {self.actor_id[:8]} died while task {spec.name!r} was in flight"
            )


def function_id_of(pickled: bytes) -> str:
    return hashlib.blake2b(pickled, digest_size=16).hexdigest()


class CoreWorker:
    """One per process. Owns the event-loop-side runtime state."""

    def __init__(
        self,
        *,
        job_id: str,
        session_name: str,
        node_id: str,
        gcs_conn: rpc.Connection,
        raylet_conn: rpc.Connection,
        is_driver: bool,
        worker_id: str,
        server: rpc.Server,
    ):
        self.job_id = job_id
        self.session_name = session_name
        self.node_id = node_id
        self.gcs = GcsClient(gcs_conn)
        self.raylet_conn = raylet_conn
        self.is_driver = is_driver
        self.worker_id = worker_id
        self.server = server  # shared rpc server (object server + task server)
        self.addr: Optional[Tuple[str, int]] = None  # set after server start
        self.raylet_addr: Optional[Tuple[str, int]] = None

        self.memory_store = MemoryStore()
        self.plasma = PlasmaClient(raylet_conn)
        self.reference_table = ReferenceTable()
        self.lease_pool = LeasePool(self)
        self.actor_submitters: Dict[str, ActorSubmitter] = {}
        self._conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._func_ids_exported: set = set()
        self._task_events: List[dict] = []
        self._free_queue: List[str] = []
        self._release_queue: List[str] = []
        # Single-hold releases from value finalizers; appended from whatever
        # thread runs GC (list.append is atomic), drained by the flush loop.
        self._release_one_queue: List[str] = []
        # task_id -> {"cancelled": bool, "conn": live worker conn or None}
        self._inflight_tasks: Dict[str, dict] = {}
        self._oid_to_task: Dict[str, str] = {}
        # Lineage: oid -> {"wire": producing TaskSpec wire, "attempts": int}.
        # Lost plasma-resident task returns are recomputed by re-running the
        # producing task (reference: object_recovery_manager.h:41 +
        # task_manager.cc; deterministic return ids from ids.py make the
        # recomputed object land under the same id).
        self.lineage: Dict[str, dict] = {}
        self._recovering: Dict[str, asyncio.Future] = {}
        self.closed = False
        self._bg_tasks: List[asyncio.Task] = []

        server.register("GetObject", self._handle_get_object)
        server.register("WaitObject", self._handle_wait_object)
        server.register("RecoverObject", self._handle_recover_object)
        server.register("Ping", self._handle_ping)

    def start_background(self) -> None:
        self._bg_tasks.append(rpc.spawn(self._flush_loop()))

    async def _flush_loop(self) -> None:
        while not self.closed:
            await asyncio.sleep(1.0)
            await self._flush_free_queue()
            await self._flush_release_queue()
            await self._flush_release_one_queue()
            await self._flush_task_events()

    async def _flush_release_queue(self) -> None:
        if not self._release_queue:
            return
        oids, self._release_queue = self._release_queue, []
        await self.plasma.release_many(oids)

    async def _flush_release_one_queue(self) -> None:
        if not self._release_one_queue:
            return
        oids, self._release_one_queue = self._release_one_queue, []
        counts: Dict[str, int] = {}
        for oid in oids:
            counts[oid] = counts.get(oid, 0) + 1
        await self.plasma.release_counts(counts)

    async def _flush_free_queue(self) -> None:
        if not self._free_queue:
            return
        oids, self._free_queue = self._free_queue, []
        to_delete_local = []
        for oid in oids:
            entry = self.memory_store.get(oid)
            self.memory_store.delete(oid)
            if entry is not None and entry.kind == IN_PLASMA:
                if entry.plasma_addr == self.raylet_addr:
                    to_delete_local.append(oid)
                else:
                    rpc.spawn(self._delete_remote(oid, entry.plasma_addr))
        if to_delete_local:
            try:
                await self.plasma.delete(to_delete_local)
            except rpc.RpcError:
                pass

    async def _delete_remote(self, oid: str, addr) -> None:
        try:
            conn = await self.connect_to(tuple(addr))
            await conn.call("ObjDelete", {"oids": [oid]})
        except rpc.RpcError:
            pass

    async def _flush_task_events(self) -> None:
        if not self._task_events:
            return
        events, self._task_events = self._task_events, []
        try:
            await self.gcs.call("AddTaskEvents", {"events": events})
        except rpc.RpcError:
            pass

    def record_task_event(self, task_id: str, name: str, state: str, **extra) -> None:
        self._task_events.append(
            {
                "task_id": task_id,
                "name": name,
                "state": state,
                "job_id": self.job_id,
                "worker_id": self.worker_id,
                "node_id": self.node_id,
                "time": time.time(),
                **extra,
            }
        )

    def schedule_free(self, oid: str) -> None:
        self._free_queue.append(oid)
        self.lineage.pop(oid, None)

    def schedule_release(self, oid: str) -> None:
        self._release_queue.append(oid)

    async def connect_to(self, addr: Tuple[str, int]) -> rpc.Connection:
        addr = tuple(addr)
        conn = self._conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(*addr, handlers=self.server._handlers)
            self._conns[addr] = conn
        return conn

    # ------------------------------------------------------------------ put

    async def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random().hex()
        await self.put_with_id(oid, value)
        ref = ObjectRef(oid, self.addr, self)
        self.reference_table.mark_owned(oid)
        return ref

    async def put_with_id(self, oid: str, value: Any) -> None:
        serialized = serialization.serialize(value)
        if serialized.total_size <= config.max_direct_call_object_size:
            self.memory_store.put_inline(oid, serialized.to_bytes())
        else:
            await self.plasma.put_serialized(oid, serialized)
            self.memory_store.put_plasma_marker(oid, self.raylet_addr)

    # ------------------------------------------------------------------ get

    async def get_objects(self, refs: List[ObjectRef], timeout: Optional[float]) -> List[Any]:
        single = False
        if isinstance(refs, ObjectRef):
            refs, single = [refs], True
        deadline = time.monotonic() + timeout if timeout is not None else None
        payloads = await asyncio.gather(
            *(self._resolve_payload(r, deadline) for r in refs)
        )
        values = []
        with serialization.DeserializationContext(
            ref_deserializer=self._deserialize_ref
        ):
            for ref, payload in zip(refs, payloads):
                value, is_exc = serialization.deserialize(payload)
                if is_exc:
                    raise value
                if isinstance(payload, memoryview):
                    # Plasma-backed zero-copy value: transfer one hold to the
                    # value's lifetime so the arena bytes stay mapped while
                    # the value is alive but can be spilled/evicted once it's
                    # garbage collected, even if the ObjectRef lives on
                    # (reference: plasma client buffer refcounts).
                    self._attach_value_hold(ref.hex(), value)
                values.append(value)
        return values[0] if single else values

    def _queue_release_one(self, oid: str) -> None:
        # Bound method (not list.append) so finalizers always reach the
        # *current* queue — the flush loop swaps the list object out.
        self._release_one_queue.append(oid)

    def _attach_value_hold(self, oid: str, value: Any) -> None:
        import weakref

        try:
            weakref.finalize(value, self._queue_release_one, oid)
        except TypeError:
            # Not weakref-able (plain containers/scalars): the hold stays
            # tied to the ObjectRef lifetime (conservative; no corruption,
            # but such objects cannot be spilled while referenced).
            pass

    def _deserialize_ref(self, hex_id, owner_addr):
        return ObjectRef(hex_id, owner_addr, self)

    def _remaining(self, deadline) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise GetTimeoutError("get timed out")
        return rem

    async def _resolve_payload(self, ref: ObjectRef, deadline) -> bytes:
        oid = ref.hex()
        entry = self.memory_store.get(oid)
        owned = oid in self.reference_table.entries and self.reference_table.entries[oid].owned
        if entry is None and owned:
            entry = await self.memory_store.wait_for(oid, self._remaining(deadline))
            if entry is None:
                raise GetTimeoutError(f"timed out waiting for {oid[:12]}")
        if entry is not None:
            if entry.kind == INLINE:
                return entry.payload
            recoveries = 0
            while True:
                try:
                    return await self._fetch_plasma(oid, entry.plasma_addr, deadline)
                except (ObjectLostError, rpc.RpcError):
                    # Primary copy gone (node death, eviction). If we own it
                    # and have lineage, recompute; else propagate.
                    if not owned or recoveries >= config.max_lineage_reconstruction:
                        raise
                    recoveries += 1
                    await self.recover_object(oid)
                    entry = self.memory_store.get(oid)
                    if entry is None:
                        raise ObjectLostError(
                            f"object {oid[:12]} lost and reconstruction "
                            "produced no value"
                        )
                    if entry.kind == INLINE:
                        return entry.payload
        # Borrowed ref: try local plasma first (common when the primary copy
        # is on our node), else ask the owner.
        found, _ = await self.plasma.get([oid], block=False)
        if oid in found:
            return found[oid]
        return await self._fetch_from_owner(ref, deadline)

    async def _fetch_plasma(self, oid: str, plasma_addr, deadline) -> memoryview:
        if tuple(plasma_addr) == self.raylet_addr:
            found, missing = await self.plasma.get([oid], timeout=self._remaining(deadline))
            if oid in found:
                return found[oid]
            raise ObjectLostError(f"object {oid[:12]} lost from local store")
        return await self.plasma.pull(oid, tuple(plasma_addr))

    async def _fetch_from_owner(self, ref: ObjectRef, deadline) -> bytes:
        if ref.owner_addr is None:
            raise ObjectLostError(f"no owner known for {ref.hex()[:12]}")
        if tuple(ref.owner_addr) == self.addr:
            # We are the owner but have no entry: freed or never created.
            raise ObjectLostError(f"object {ref.hex()[:12]} no longer exists on owner")
        try:
            conn = await self.connect_to(ref.owner_addr)
            reply = await conn.call(
                "GetObject",
                {"oid": ref.hex(), "timeout": self._remaining(deadline)},
                timeout=None,
            )
        except rpc.ConnectionLost as e:
            # The owner process is gone; with it goes the object's directory
            # entry and any lineage (reference: OwnerDiedError).
            raise ObjectLostError(
                f"owner of {ref.hex()[:12]} at {tuple(ref.owner_addr)} is "
                f"unreachable ({e}); object cannot be recovered"
            ) from e
        for _ in range(config.max_lineage_reconstruction + 1):
            status = reply.get("status")
            if status == "inline":
                return reply["payload"]
            if status == "plasma":
                try:
                    return await self._fetch_plasma(
                        ref.hex(), tuple(reply["addr"]), deadline
                    )
                except (ObjectLostError, rpc.RpcError):
                    # Primary copy unreachable; ask the owner to recover it
                    # (lineage re-execution on the owner side) and retry with
                    # the fresh location.
                    try:
                        reply = await conn.call(
                            "RecoverObject",
                            {"oid": ref.hex(), "timeout": self._remaining(deadline)},
                            timeout=None,
                        )
                    except rpc.ConnectionLost as e:
                        raise ObjectLostError(
                            f"owner of {ref.hex()[:12]} died during recovery "
                            f"({e}); object cannot be recovered"
                        ) from e
                    continue
            if status == "timeout":
                raise GetTimeoutError(f"owner timed out resolving {ref.hex()[:12]}")
            raise ObjectLostError(
                f"owner reports {ref.hex()[:12]}: {status}"
                + (f" ({reply['error']})" if reply.get("error") else "")
            )
        raise ObjectLostError(
            f"object {ref.hex()[:12]} unrecoverable after repeated owner recovery"
        )

    # -- owner-side object server -------------------------------------------

    async def _handle_get_object(self, conn, p):
        entry = await self.memory_store.wait_for(p["oid"], p.get("timeout", 300))
        if entry is None:
            known = p["oid"] in self.reference_table.entries
            return {"status": "timeout" if known else "unknown"}
        if entry.kind == INLINE:
            return {"status": "inline", "payload": entry.payload}
        return {"status": "plasma", "addr": list(entry.plasma_addr)}

    async def _handle_wait_object(self, conn, p):
        entry = await self.memory_store.wait_for(p["oid"], p.get("timeout"))
        return {"ready": entry is not None}

    async def _handle_recover_object(self, conn, p):
        """Borrower reports our object's primary copy lost; reconstruct via
        lineage and reply with the fresh location."""
        oid = p["oid"]
        try:
            await self.recover_object(oid)
        except ObjectLostError as e:
            return {"status": "lost", "error": str(e)}
        entry = await self.memory_store.wait_for(oid, p.get("timeout") or 300)
        if entry is None:
            return {"status": "timeout"}
        if entry.kind == INLINE:
            return {"status": "inline", "payload": entry.payload}
        return {"status": "plasma", "addr": list(entry.plasma_addr)}

    # ------------------------------------------------- lineage reconstruction

    def _register_lineage(self, spec: TaskSpec, reply: dict) -> None:
        """Remember the producing spec for every plasma-resident return so a
        lost copy can be recomputed (inline returns live in this process and
        die with the owner, at which point all refs die too)."""
        plasma_oids = []
        for oid, ret in zip(spec.return_ids, reply.get("returns") or []):
            if "plasma" in ret:
                plasma_oids.append(oid)
        if reply.get("dynamic") is not None:
            for i, ret in enumerate(reply["dynamic"]):
                if "plasma" in ret:
                    plasma_oids.append(
                        deterministic_object_id(
                            TaskID.from_hex(spec.task_id), i + 1
                        ).hex()
                    )
        if not plasma_oids:
            return
        wire = spec.to_wire()
        for oid in plasma_oids:
            prev = self.lineage.get(oid)
            self.lineage[oid] = {
                "wire": wire,
                # A reconstruction-driven re-run must not refill the attempt
                # budget, or a flaky node makes the cap unreachable.
                "attempts": (
                    prev["attempts"]
                    if prev is not None
                    else config.max_lineage_reconstruction
                ),
            }

    async def recover_object(self, oid: str) -> None:
        """Re-execute the producing task of a lost object (owner side).

        Deduplicates concurrent recoveries per producing task (one re-execution
        regenerates every return of that task); recursive losses resolve
        naturally because the re-executed task's worker pulls its args through
        this same get path (recursing borrower->owner).
        Reference: src/ray/core_worker/object_recovery_manager.h:41.
        """
        entry = self.lineage.get(oid)
        if entry is None:
            raise ObjectLostError(
                f"object {oid[:12]} lost and has no lineage "
                "(ray.put objects and actor-task returns are not reconstructable)"
            )
        task_id = entry["wire"]["task_id"]
        fut = self._recovering.get(task_id)
        if fut is not None:
            await fut
            return
        if entry["attempts"] <= 0:
            raise ObjectLostError(
                f"object {oid[:12]} lost; lineage reconstruction attempts exhausted"
            )
        entry["attempts"] -= 1
        fut = asyncio.get_running_loop().create_future()
        self._recovering[task_id] = fut
        spec = TaskSpec.from_wire(dict(entry["wire"]))
        logger.info(
            "reconstructing object %s by re-running task %r",
            oid[:12],
            spec.name,
        )
        self.record_task_event(spec.task_id, spec.name, "RECONSTRUCTING")
        # Re-install the submission bookkeeping that _run_task's finally
        # clause tears down.
        self._inflight_tasks[spec.task_id] = {"cancelled": False, "conn": None}
        for rid in spec.return_ids:
            self._oid_to_task[rid] = spec.task_id
        for dep_oid, _ in spec.dependencies:
            self.reference_table.add_submitted(dep_oid)
        try:
            await self._run_task(spec.to_wire(), spec)
            fut.set_result(None)
        except BaseException as e:
            fut.set_exception(e)
            # Consume it if nobody else awaits the future.
            fut.exception()
            raise
        finally:
            self._recovering.pop(task_id, None)

    async def _handle_ping(self, conn, p):
        return {"pong": True, "worker_id": self.worker_id}

    # ------------------------------------------------------------- wait

    async def wait(
        self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        ready_flags: Dict[int, bool] = {}

        async def probe(i, ref):
            try:
                await self._wait_available(ref, None)
                ready_flags[i] = True
            except asyncio.CancelledError:
                pass

        tasks = [rpc.spawn(probe(i, r)) for i, r in enumerate(refs)]
        deadline = time.monotonic() + timeout if timeout is not None else None
        try:
            while len(ready_flags) < num_returns:
                pending = [t for t in tasks if not t.done()]
                if not pending:
                    break
                rem = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    pending, timeout=rem, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break  # timeout
        finally:
            for t in tasks:
                t.cancel()
        ready = [r for i, r in enumerate(refs) if ready_flags.get(i)]
        not_ready = [r for i, r in enumerate(refs) if not ready_flags.get(i)]
        return ready, not_ready

    async def _wait_available(self, ref: ObjectRef, timeout) -> None:
        oid = ref.hex()
        entry = self.memory_store.get(oid)
        if entry is not None:
            return
        owned = oid in self.reference_table.entries and self.reference_table.entries[oid].owned
        if owned:
            entry = await self.memory_store.wait_for(oid, timeout)
            if entry is None:
                raise GetTimeoutError(oid)
            return
        contains = await self.plasma.contains([oid])
        if contains.get(oid):
            return
        if ref.owner_addr is None or tuple(ref.owner_addr) == self.addr:
            entry = await self.memory_store.wait_for(oid, timeout)
            if entry is None:
                raise GetTimeoutError(oid)
            return
        conn = await self.connect_to(ref.owner_addr)
        await conn.call("WaitObject", {"oid": oid, "timeout": timeout}, timeout=None)

    # ----------------------------------------------------- function export

    async def export_function(self, pickled_fn: bytes) -> str:
        func_id = function_id_of(pickled_fn)
        if func_id not in self._func_ids_exported:
            await self.gcs.kv_put(func_id, pickled_fn, ns="fn", overwrite=False)
            self._func_ids_exported.add(func_id)
        return func_id

    # ------------------------------------------------------- task submission

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Serialize the call arguments; returns (blob_info, deps).

        Top-level ObjectRef args are replaced by positional markers resolved
        by the executor to values (reference semantics); nested refs pass
        through as refs. A large blob moves via the shm store.
        """
        ref_positions = []
        plain_args = list(args)
        for i, a in enumerate(plain_args):
            if isinstance(a, ObjectRef):
                ref_positions.append(i)
        kw_ref_keys = [k for k, v in kwargs.items() if isinstance(v, ObjectRef)]
        serialized = serialization.serialize((plain_args, kwargs))
        deps = []
        for r in serialized.contained_refs:
            deps.append((r.hex(), list(r.owner_addr) if r.owner_addr else None))
        return serialized, ref_positions, kw_ref_keys, deps

    async def submit_task(
        self,
        pickled_fn: bytes,
        fn_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: Optional[int] = None,
        retry_exceptions: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        if runtime_env:
            from ray_tpu.runtime_env.context import prepare

            runtime_env = await prepare(self, runtime_env)
        if num_returns == "dynamic":
            num_returns = -1
        func_id = await self.export_function(pickled_fn)
        task_id = TaskID.from_random().hex()
        return_ids = [
            deterministic_object_id(TaskID.from_hex(task_id), i).hex()
            for i in range(1 if num_returns == -1 else num_returns)
        ]
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        args_blob, args_object = None, None
        if serialized.total_size <= config.max_direct_call_object_size:
            args_blob = serialized.to_bytes()
        else:
            args_object = ObjectID.from_random().hex()
            await self.plasma.put_serialized(args_object, serialized)
            self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
            self.reference_table.mark_owned(args_object)
            self.reference_table.add_local(args_object)

        res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=fn_name,
            func_id=func_id,
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=res.to_units(),
            max_retries=(
                max_retries if max_retries is not None else config.default_max_task_retries
            ),
            retry_exceptions=retry_exceptions,
            owner_addr=list(self.addr),
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env,
        )
        wire = spec.to_wire()

        refs = []
        for oid in return_ids:
            self.reference_table.mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        self.record_task_event(task_id, fn_name, "PENDING")
        self._inflight_tasks[task_id] = {"cancelled": False, "conn": None}
        for oid in return_ids:
            self._oid_to_task[oid] = task_id
        rpc.spawn(self._run_task(wire, spec))
        return refs

    def try_submit_task_fast(
        self,
        pickled_fn: bytes,
        fn_name: str,
        args: tuple,
        kwargs: dict,
        *,
        loop,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: Optional[int] = None,
        retry_exceptions: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> Optional[List[ObjectRef]]:
        """Synchronous submission fast path, callable from any thread.

        The hot-path cost of .remote() is not the work but the thread
        round-trip into the event loop (run_coroutine_threadsafe + wait).
        Everything except launching the network I/O is thread-safe to do
        here: serialization uses thread-local context, id generation is
        random, the reference table takes a lock, and the remaining
        bookkeeping is GIL-atomic appends/inserts. Only the launch is posted
        (fire-and-forget) onto the loop. Returns None when this call needs
        the async slow path (runtime_env prep, first-time function export,
        or plasma-resident args).
        """
        if runtime_env:
            return None
        func_id = function_id_of(pickled_fn)
        if func_id not in self._func_ids_exported:
            return None  # first call pays the async export
        if num_returns == "dynamic":
            num_returns = -1
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        if serialized.total_size > config.max_direct_call_object_size:
            return None  # large args need an async plasma write
        task_id = TaskID.from_random().hex()
        return_ids = [
            deterministic_object_id(TaskID.from_hex(task_id), i).hex()
            for i in range(1 if num_returns == -1 else num_returns)
        ]
        res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=fn_name,
            func_id=func_id,
            args_blob=serialized.to_bytes(),
            args_object=None,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=res.to_units(),
            max_retries=(
                max_retries
                if max_retries is not None
                else config.default_max_task_retries
            ),
            retry_exceptions=retry_exceptions,
            owner_addr=list(self.addr),
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=None,
        )
        wire = spec.to_wire()
        refs = []
        for oid in return_ids:
            self.reference_table.mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        self.record_task_event(task_id, fn_name, "PENDING")
        self._inflight_tasks[task_id] = {"cancelled": False, "conn": None}
        for oid in return_ids:
            self._oid_to_task[oid] = task_id
        loop.call_soon_threadsafe(self._spawn_run_task, wire, spec)
        return refs

    def _spawn_run_task(self, wire: dict, spec: TaskSpec) -> None:
        rpc.spawn(self._run_task(wire, spec))

    async def cancel(self, ref: "ObjectRef", force: bool = False) -> bool:
        """Best-effort task cancellation (reference: ray.cancel ->
        CoreWorker::CancelTask). Queued tasks are dropped; running tasks get
        a TaskCancelledError raised in their executing thread/coroutine."""
        task_id = self._oid_to_task.get(ref.hex())
        if task_id is None:
            return False
        entry = self._inflight_tasks.get(task_id)
        if entry is None:
            return False  # already finished
        entry["cancelled"] = True
        conn = entry.get("conn")
        if conn is not None and not conn.closed:
            try:
                await conn.call(
                    "CancelTask", {"task_id": task_id, "force": force}, timeout=10
                )
            except rpc.RpcError:
                pass
        return True

    async def _run_task(self, wire: dict, spec: TaskSpec) -> None:
        try:
            await self._wait_for_deps(spec.dependencies)
            attempts = spec.max_retries + 1
            last_err: Optional[Exception] = None
            for attempt in range(attempts):
                entry = self._inflight_tasks.get(spec.task_id)
                if entry is not None and entry["cancelled"]:
                    self._store_task_error(
                        spec, TaskCancelledError(f"task {spec.name} was cancelled")
                    )
                    self.record_task_event(spec.task_id, spec.name, "CANCELLED")
                    return
                try:
                    reply = await self._lease_and_push(wire, spec)
                    self._store_task_results(spec, reply)
                    if reply.get("error") is None and spec.actor_id is None:
                        self._register_lineage(spec, reply)
                    self.record_task_event(spec.task_id, spec.name, "FINISHED")
                    return
                except (rpc.ConnectionLost, WorkerCrashedError) as e:
                    last_err = e
                    entry = self._inflight_tasks.get(spec.task_id)
                    if entry is not None and entry["cancelled"]:
                        self._store_task_error(
                            spec,
                            TaskCancelledError(f"task {spec.name} was cancelled"),
                        )
                        return
                    self.record_task_event(
                        spec.task_id, spec.name, "RETRY", attempt=attempt
                    )
                    logger.warning(
                        "task %s attempt %d failed (%s); retrying",
                        spec.name,
                        attempt,
                        e,
                    )
                    await asyncio.sleep(min(1.0, 0.1 * (attempt + 1)))
            self._store_task_error(
                spec, WorkerCrashedError(f"task {spec.name} failed after retries: {last_err}")
            )
        except Exception as e:
            logger.exception("task %s submission failed", spec.name)
            self._store_task_error(spec, e)
        finally:
            self._inflight_tasks.pop(spec.task_id, None)
            for oid in spec.return_ids:
                self._oid_to_task.pop(oid, None)
            for dep_oid, _ in spec.dependencies:
                self.reference_table.remove_submitted(dep_oid, self)

    async def _wait_for_deps(self, deps) -> None:
        waits = []
        for oid, owner in deps:
            ref = ObjectRef(oid, tuple(owner) if owner else None, self)
            waits.append(self._wait_available(ref, 300))
        if waits:
            await asyncio.gather(*waits)

    async def _lease_and_push(self, wire: dict, spec: TaskSpec) -> dict:
        lease = await self.lease_pool.acquire(
            spec.resources, spec.pg_id, spec.bundle_index
        )
        dirty = False
        entry = self._inflight_tasks.get(spec.task_id)
        if entry is not None:
            if entry["cancelled"]:
                # Cancellation landed while we were queued for a lease.
                await self.lease_pool.release(
                    lease, spec.resources, spec.pg_id, spec.bundle_index
                )
                raise TaskCancelledError(f"task {spec.name} was cancelled")
            entry["conn"] = lease.conn
        try:
            self.record_task_event(spec.task_id, spec.name, "RUNNING")
            return await lease.conn.call("PushTask", {"spec": wire}, timeout=None)
        except rpc.ConnectionLost:
            dirty = True
            raise
        finally:
            if entry is not None:
                entry["conn"] = None
            await self.lease_pool.release(
                lease, spec.resources, spec.pg_id, spec.bundle_index, dirty=dirty
            )

    def _store_task_results(self, spec: TaskSpec, reply: dict) -> None:
        if reply.get("error") is not None:
            payload = reply["error"]
            for oid in spec.return_ids:
                self.memory_store.put_inline(oid, payload)
            self.record_task_event(spec.task_id, spec.name, "FAILED")
            return
        if reply.get("dynamic") is not None:
            # Streaming-generator task: store each yielded item under its
            # deterministic id and make the main return value an
            # ObjectRefGenerator over them.
            refs = []
            for i, ret in enumerate(reply["dynamic"]):
                oid = deterministic_object_id(
                    TaskID.from_hex(spec.task_id), i + 1
                ).hex()
                if "inline" in ret:
                    self.memory_store.put_inline(oid, ret["inline"])
                else:
                    self.memory_store.put_plasma_marker(oid, tuple(ret["plasma"]))
                self.reference_table.mark_owned(oid)
                refs.append(ObjectRef(oid, self.addr, self))
            gen = ObjectRefGenerator(refs)
            self.memory_store.put_inline(
                spec.return_ids[0], serialization.serialize(gen).to_bytes()
            )
            return
        returns = reply["returns"]
        for oid, ret in zip(spec.return_ids, returns):
            if "inline" in ret:
                self.memory_store.put_inline(oid, ret["inline"])
            else:
                self.memory_store.put_plasma_marker(oid, tuple(ret["plasma"]))

    def _store_task_error(self, spec: TaskSpec, exc: Exception) -> None:
        serialized = serialization.serialize(exc)
        payload = serialized.to_bytes()
        for oid in spec.return_ids:
            self.memory_store.put_inline(oid, payload)
        self.record_task_event(spec.task_id, spec.name, "FAILED")

    # ----------------------------------------------------------- actors

    async def create_actor(
        self,
        pickled_cls: bytes,
        cls_name: str,
        args: tuple,
        kwargs: dict,
        *,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        lifetime: Optional[str] = None,
        get_if_exists: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> str:
        if runtime_env:
            from ray_tpu.runtime_env.context import prepare

            runtime_env = await prepare(self, runtime_env)
        func_id = await self.export_function(pickled_cls)
        actor_id = ActorID.from_random().hex()
        task_id = TaskID.from_random().hex()
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        strategy = dict(scheduling_strategy or {})
        if lifetime == "detached":
            strategy["detached"] = True
        res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
        args_blob, args_object = None, None
        if serialized.total_size <= config.max_direct_call_object_size:
            args_blob = serialized.to_bytes()
        else:
            args_object = ObjectID.from_random().hex()
            await self.plasma.put_serialized(args_object, serialized)
            self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=cls_name,
            func_id=func_id,
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=0,
            return_ids=[],
            resources=res.to_units(),
            owner_addr=list(self.addr),
            actor_id=actor_id,
            actor_creation=True,
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=strategy,
            runtime_env=runtime_env,
            actor_name=name,
            namespace=namespace,
        )
        wire = spec.to_wire()
        reply = await self.gcs.call(
            "CreateActor",
            {"spec": wire, "wait_alive": False, "get_if_exists": get_if_exists},
            timeout=None,
        )
        if reply.get("existing"):
            return reply["actor"]["actor_id"]
        return actor_id

    def _submitter(self, actor_id: str) -> ActorSubmitter:
        sub = self.actor_submitters.get(actor_id)
        if sub is None:
            sub = self.actor_submitters[actor_id] = ActorSubmitter(self, actor_id)
        return sub

    async def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
    ) -> List[ObjectRef]:
        task_id = TaskID.from_random().hex()
        return_ids = [
            deterministic_object_id(TaskID.from_hex(task_id), i).hex()
            for i in range(num_returns)
        ]
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        args_blob, args_object = None, None
        if serialized.total_size <= config.max_direct_call_object_size:
            args_blob = serialized.to_bytes()
        else:
            args_object = ObjectID.from_random().hex()
            await self.plasma.put_serialized(args_object, serialized)
            self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=method_name,
            func_id="",
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources={},
            owner_addr=list(self.addr),
            actor_id=actor_id,
            actor_method=method_name,
            caller_id=self.worker_id,
        )
        refs = []
        for oid in return_ids:
            self.reference_table.mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        rpc.spawn(self._run_actor_task(spec))
        return refs

    def try_submit_actor_task_fast(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        loop,
        num_returns: int = 1,
    ) -> Optional[List[ObjectRef]]:
        """Synchronous actor-call fast path (see try_submit_task_fast)."""
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        if serialized.total_size > config.max_direct_call_object_size:
            return None
        task_id = TaskID.from_random().hex()
        return_ids = [
            deterministic_object_id(TaskID.from_hex(task_id), i).hex()
            for i in range(num_returns)
        ]
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=method_name,
            func_id="",
            args_blob=serialized.to_bytes(),
            args_object=None,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources={},
            owner_addr=list(self.addr),
            actor_id=actor_id,
            actor_method=method_name,
            caller_id=self.worker_id,
        )
        refs = []
        for oid in return_ids:
            self.reference_table.mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        loop.call_soon_threadsafe(self._spawn_run_actor_task, spec)
        return refs

    def _spawn_run_actor_task(self, spec: TaskSpec) -> None:
        rpc.spawn(self._run_actor_task(spec))

    async def _run_actor_task(self, spec: TaskSpec) -> None:
        try:
            await self._wait_for_deps(spec.dependencies)
            sub = self._submitter(spec.actor_id)
            reply = await sub.submit(spec)
            self._store_task_results(spec, reply)
        except Exception as e:
            self._store_task_error(spec, e)
        finally:
            self._inflight_tasks.pop(spec.task_id, None)
            for oid in spec.return_ids:
                self._oid_to_task.pop(oid, None)
            for dep_oid, _ in spec.dependencies:
                self.reference_table.remove_submitted(dep_oid, self)

    async def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        await self.gcs.call("KillActor", {"actor_id": actor_id, "no_restart": no_restart})

    # ---------------------------------------------------------- shutdown

    async def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for t in self._bg_tasks:
            t.cancel()
        await self._flush_task_events()
        await self.lease_pool.drain()
        self.plasma.close()
        for conn in self._conns.values():
            await conn.close()
