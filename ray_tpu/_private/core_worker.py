"""CoreWorker: the in-process runtime embedded in every driver and worker.

TPU-native analog of the reference's CoreWorker (src/ray/core_worker/core_worker.h:292):
Put/Get/Wait, task submission over leased workers (direct task transport —
transport/direct_task_transport.h:75), direct actor submission with per-handle
sequence numbers (transport/sequential_actor_submit_queue.cc), ownership-based
reference counting (reference_count.cc), task retries (task_manager.cc), and an
object server so borrowers can pull owner-local objects.

Everything here is async and runs on the process's event loop; the public sync
API (ray_tpu/_private/worker.py) bridges via run_coroutine_threadsafe.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
import traceback
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import rpc, serialization, telemetry
from ray_tpu._private.common import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    ObjectReconstructionFailedError,
    ResourceSet,
    TaskCancelledError,
    TaskError,
    TaskSpec,
    WorkerCrashedError,
    config,
)
from ray_tpu._private.gcs import GcsClient
from ray_tpu._private.ids import (
    ActorID,
    ObjectID,
    TaskID,
    deterministic_object_id,
    fast_unique_hex,
    return_object_ids,
)
from ray_tpu._private.object_store import IN_PLASMA, INLINE, MemoryStore, PlasmaClient

logger = logging.getLogger(__name__)

_TEL_RECONSTRUCTIONS = telemetry.counter(
    "object", "reconstructions",
    "lineage reconstructions of lost objects, by outcome "
    "(ok = producer re-ran and the value is back; failed = re-execution "
    "failed, attempts exhausted, depth cap hit, or no lineage existed; "
    "pruned = the producing spec was dropped under lineage_bytes_limit)",
)
_TEL_RECON_OK = _TEL_RECONSTRUCTIONS.cell(outcome="ok")
_TEL_RECON_FAILED = _TEL_RECONSTRUCTIONS.cell(outcome="failed")
_TEL_RECON_PRUNED = _TEL_RECONSTRUCTIONS.cell(outcome="pruned")
_TEL_LINEAGE_BYTES = telemetry.gauge(
    "object", "lineage_bytes",
    "bytes of retained producing TaskSpecs (bounded by lineage_bytes_limit)",
)


class ObjectRefGenerator:
    """Value of a num_returns="dynamic" task: an iterable of ObjectRefs.

    Streaming (reference: the ReportGeneratorItemReturns path +
    TryReadObjectRefStream, core_worker.h:389): the producing worker ships
    each yielded item as it is produced, so iterating here overlaps the
    producer — ``__iter__`` yields the ref for item i as soon as the owner
    has it, blocking only on items not yet produced. ``len()`` blocks until
    the producer finishes. A generator constructed with a plain ref list
    (legacy / fully-materialized) behaves statically.
    """

    def __init__(self, refs=None, task_id=None, owner_addr=None, total=None):
        self._refs = list(refs) if refs is not None else None
        self._task_id = task_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._total = total
        # Streaming mode: hold a ref on the stream's return object so the
        # owner keeps the stream state (and item bookkeeping) alive for as
        # long as any generator handle exists — the caller usually drops the
        # raw task ref right after ray.get()ing this generator.
        self._stream_ref = None
        if task_id is not None:
            try:
                from ray_tpu._private import worker as worker_mod

                core = worker_mod.global_worker.core
                if core is not None and not core.closed:
                    rid = return_object_ids(task_id, 1)[0]
                    self._stream_ref = ObjectRef(rid, self._owner_addr, core)
            except Exception:
                pass

    # -- streaming plumbing --------------------------------------------------

    def _next_ref(self, i: int):
        """Blocking: ref for item i, or None when the stream ended before i."""
        if self._refs is not None:
            return self._refs[i] if i < len(self._refs) else None
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        return w.run_async(
            w.core.dyn_next(self._task_id, self._owner_addr, i), timeout=600
        )

    def __iter__(self):
        if self._refs is not None:
            return iter(list(self._refs))

        def it(gen=self):
            i = 0
            while True:
                ref = gen._next_ref(i)
                if ref is None:
                    return
                yield ref
                i += 1

        return it()

    def __len__(self):
        if self._refs is not None:
            return len(self._refs)
        if self._total is None:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            self._total = w.run_async(
                w.core.dyn_total(self._task_id, self._owner_addr), timeout=600
            )
        return self._total

    def __getitem__(self, i):
        if self._refs is not None:
            return self._refs[i]
        ref = self._next_ref(i)
        if ref is None:
            raise IndexError(i)
        return ref

    def __reduce__(self):
        if self._refs is not None:
            return (ObjectRefGenerator, (self._refs,))
        # The stream's return object is this generator's dependency: record
        # it so task-arg serialization pins the stream while in flight.
        if self._stream_ref is not None:
            serialization.record_contained_ref(self._stream_ref)
        return (
            ObjectRefGenerator,
            (None, self._task_id, self._owner_addr, self._total),
        )


class ObjectRef:
    """A reference to a (possibly not-yet-computed) object.

    Carries the owner's object-server address so any holder can resolve the
    value (ownership model: the owner worker is the object's directory).
    """

    __slots__ = ("_hex", "_owner_addr", "_core", "__weakref__")

    def __init__(self, hex_id: str, owner_addr: Tuple[str, int], core: Optional["CoreWorker"] = None):
        self._hex = hex_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._core = core
        if core is not None:
            core.reference_table.add_local(hex_id)

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    @property
    def owner_addr(self):
        return self._owner_addr

    def __repr__(self):
        return f"ObjectRef({self._hex})"

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._hex == self._hex

    def __reduce__(self):
        serialization.record_contained_ref(self)
        deserializer = serialization.get_ref_deserializer()
        if deserializer is not None:
            return (deserializer, (self._hex, self._owner_addr))
        return (_plain_ref, (self._hex, self._owner_addr))

    def __del__(self):
        core = self._core
        if core is not None and not core.closed:
            try:
                core.reference_table.remove_local(self._hex, core)
            except Exception:
                pass

    def __await__(self):
        # Allows `await ref` inside async actors.
        core = self._core
        if core is None:
            raise RuntimeError("ObjectRef is not attached to a core worker")
        return core.get_objects([self], timeout=None).__await__()


def _plain_ref(hex_id, owner_addr):
    # Deserialized outside any worker context (e.g. in a subprocess tool):
    # ref without a core; get() requires re-attachment.
    return ObjectRef(hex_id, owner_addr, None)


class RefEntry:
    __slots__ = ("local", "submitted", "owned", "freed")

    def __init__(self):
        self.local = 0
        self.submitted = 0
        self.owned = False
        self.freed = False


class ReferenceTable:
    """Per-process reference counts driving object lifetime.

    Owner frees the object (memory store entry + shm primary copy) once the
    local python refcount and in-flight-task count both reach zero.
    Reference: src/ray/core_worker/reference_count.cc (we implement the
    owner-side protocol; cross-worker borrow counts are conservatively
    approximated by the submitted-task count).

    Thread-safe: mutated both from the event loop and from user threads
    (ObjectRef ctor/__del__, the synchronous submission fast path).
    """

    def __init__(self):
        import threading

        self.entries: Dict[str, RefEntry] = {}
        self._lock = threading.Lock()

    def _entry(self, oid: str) -> RefEntry:
        e = self.entries.get(oid)
        if e is None:
            e = self.entries[oid] = RefEntry()
        return e

    def add_local(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).local += 1

    def register_task(self, return_ids, dep_oids) -> None:
        """Submission-time registration under ONE lock acquire (hot path):
        mark returns owned, count deps as submitted."""
        with self._lock:
            for oid in return_ids:
                self._entry(oid).owned = True
            for oid in dep_oids:
                self._entry(oid).submitted += 1

    def mark_owned(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).owned = True

    def add_submitted(self, oid: str) -> None:
        with self._lock:
            self._entry(oid).submitted += 1

    def remove_submitted(self, oid: str, core: "CoreWorker") -> None:
        with self._lock:
            e = self.entries.get(oid)
            if e is None:
                return
            e.submitted -= 1
            self._maybe_free(oid, e, core)

    def remove_local(self, oid: str, core: "CoreWorker") -> None:
        with self._lock:
            e = self.entries.get(oid)
            if e is None:
                return
            e.local -= 1
            self._maybe_free(oid, e, core)

    def _maybe_free(self, oid: str, e: RefEntry, core: "CoreWorker") -> None:
        # Called with the lock held; the schedule_* sinks are plain appends.
        if e.local <= 0 and e.submitted <= 0 and not e.freed:
            e.freed = True
            del self.entries[oid]
            if e.owned:
                core.schedule_free(oid)
            # Drop this process's plasma hold: with no local refs left, user
            # code keeping a zero-copy view alive past this point is outside
            # the supported contract (same as the reference's buffer release).
            if oid in core.plasma.held:
                core.schedule_release(oid)


_FP_MOD: Any = None  # None = untried; False = unavailable; module otherwise

# Cached serialized ([], {}) — the args blob of every no-arg task.
_EMPTY_ARGS: Any = None


def _fp_mod():
    """The native fastpath extension, or False when disabled/missing."""
    global _FP_MOD
    if _FP_MOD is None:
        if not config.fastpath_enabled:
            _FP_MOD = False
        else:
            try:
                from ray_tpu._native import _fastpath as m

                _FP_MOD = m
            except Exception:
                _FP_MOD = False
    return _FP_MOD


class Lease:
    __slots__ = (
        "lease_id", "worker_id", "addr", "conn", "raylet_conn",
        "outstanding", "in_idle", "checked_out", "used", "parked_at",
        "fp_port", "fp_channel",
    )

    def __init__(
        self, lease_id: str, worker_id: str, addr, conn, raylet_conn,
        fp_port=None,
    ):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.addr = tuple(addr)
        self.conn: rpc.Connection = conn
        self.raylet_conn: rpc.Connection = raylet_conn
        # Native fastpath channel (src/fastpath.cc): advertised port and the
        # lazily-opened channel id (None until first eligible dispatch;
        # False after a failed connect so we stop retrying).
        self.fp_port = fp_port
        self.fp_channel = None
        # Tasks pushed but not yet replied. The dispatcher pipelines up to
        # PIPELINE_DEPTH tasks per leased worker so the next task's frame is
        # already in the worker's socket buffer when the current one finishes
        # (the worker still executes serially; this hides the RTT).
        self.outstanding = 0
        # Membership flag for the shape pool's idle list (capacity available).
        self.in_idle = False
        # Exclusively handed to an acquire() waiter; release() clears it.
        # While set, pipelined-task reply callbacks must not repark/return it.
        self.checked_out = False
        # True once a task has been dispatched on it (SPREAD pools retire
        # used leases instead of recycling them).
        self.used = False
        # monotonic() when the lease last went fully idle (keep-alive sweep).
        self.parked_at = 0.0


class _ShapePool:
    """Per-resource-shape lease state: queued work items, idle leases, and
    in-flight lease requests to the raylet."""

    __slots__ = (
        "idle", "pending", "inflight", "inflight_ids", "inflight_reqs",
        "leases", "total_outstanding", "resources", "pg_id", "bundle_index",
        "strategy", "sweep_scheduled",
    )

    def __init__(self, resources, pg_id, bundle_index, strategy=None):
        self.idle: List[Lease] = []
        # Work items in FIFO order. Each is ("task", wire, None) — a
        # callback-dispatched task submission — or ("waiter", future, hints)
        # — an async acquire() that receives the lease itself, with optional
        # arg-locality hints ({"host:port": weight}) forwarded to the raylet
        # on the next lease request of this shape.
        self.pending: "deque" = deque()
        self.inflight = 0
        # lease_ids of in-flight RequestWorkerLease RPCs still cancellable on
        # the home raylet.
        self.inflight_ids: set = set()
        # lease_id -> (conn, msgid) of the batched request frame, so a
        # cancel landing before the batch flushes withdraws the entry
        # locally instead of sending a wire cancel for a frame that never
        # went out.
        self.inflight_reqs: dict = {}
        # Live leases of this shape (granted, not yet returned).
        self.leases: set = set()
        # Running total of outstanding pushes across self.leases (kept by
        # dispatch/reply so depth decisions don't re-sum per item).
        self.total_outstanding = 0
        self.resources = resources
        self.pg_id = pg_id
        self.bundle_index = bundle_index
        self.strategy = strategy
        # A keep-alive sweep timer is pending for this pool's parked leases.
        self.sweep_scheduled = False


class LeasePool:
    """Pipelined worker-lease dispatcher (callback-based hot path).

    Reference design: CoreWorkerDirectTaskSubmitter keeps a per-scheduling-key
    queue, pipelines one lease request per queued task (bounded), reuses
    granted workers for queued tasks of the same shape, and returns surplus
    workers to the raylet. The hot path here never creates a coroutine per
    task: `submit_task_fast` queues a wire spec, `_pump` pushes it onto an
    idle lease via `call_nowait`, and the reply callback recycles the lease
    into the next queued item (direct_task_transport.h:75 analog).
    """

    # Max in-flight RequestWorkerLease RPCs per shape (reference knob:
    # max_pending_lease_requests_per_scheduling_category).
    MAX_INFLIGHT = 16
    # Tasks pushed-but-unreplied per leased worker (execution stays serial on
    # the worker; >1 hides the push/reply RTT behind execution). Deep enough
    # that a worker's queue stays non-empty across a whole completion-drain
    # cycle on a loaded single-core host (measured: 16 leaves pipeline
    # bubbles at >10k tasks/s; 64 removes them); _allowed_depth scales this
    # down whenever the backlog is small relative to the lease supply, so
    # long-task bursts still spread.
    PIPELINE_DEPTH = 64

    def __init__(self, core: "CoreWorker"):
        self.core = core
        self.pools: Dict[tuple, _ShapePool] = {}
        # Native fastpath state: task_id -> (key, pool, lease, wire) for
        # tasks in flight on a C++ channel, and whether the completion
        # drainer is wired onto the event loop.
        self._fp_inflight: Dict[str, tuple] = {}
        self._fp_drainer_installed = False

    @staticmethod
    def shape_key(resources: Dict[str, int], pg_id, bundle_index, strategy=None) -> tuple:
        if strategy:
            import json

            # Canonical hashable form; label strategies nest dicts.
            skey = json.dumps(strategy, sort_keys=True)
        else:
            skey = None
        return (tuple(sorted((resources or {}).items())), pg_id, bundle_index, skey)

    def _pool(self, key, resources, pg_id, bundle_index, strategy=None) -> _ShapePool:
        p = self.pools.get(key)
        if p is None:
            p = self.pools[key] = _ShapePool(resources, pg_id, bundle_index, strategy)
        return p

    # -- intake --------------------------------------------------------------

    def submit_task_fast(self, wire: dict) -> None:
        """Queue a dependency-free task wire for callback dispatch."""
        strategy = wire.get("scheduling_strategy")
        key = self.shape_key(
            wire.get("resources"), wire.get("pg_id"), wire.get("bundle_index", -1),
            strategy,
        )
        pool = self._pool(
            key, wire.get("resources") or {}, wire.get("pg_id"),
            wire.get("bundle_index", -1), strategy,
        )
        pool.pending.append(("task", wire, None))
        self._pump(key, pool)

    async def acquire(
        self,
        resources: Dict[str, int],
        pg_id=None,
        bundle_index=None,
        strategy=None,
        locality: Optional[Dict[str, float]] = None,
    ) -> Lease:
        key = self.shape_key(resources, pg_id, bundle_index, strategy)
        pool = self._pool(key, resources, pg_id, bundle_index, strategy)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        pool.pending.append(("waiter", fut, locality))
        self._pump(key, pool)
        return await fut

    # -- pump: match pending work to leases ----------------------------------

    def _pump(self, key, pool: _ShapePool) -> None:
        pending = pool.pending
        if pending and pool.idle:
            # One pass per pump: prune dead leases, then fill lowest-loaded
            # leases first up to the allowed depth. O(idle log idle + items).
            live = []
            for lease in pool.idle:
                if lease.conn.closed:
                    lease.in_idle = False
                    pool.leases.discard(lease)
                else:
                    live.append(lease)
            if len(live) > 1:
                live.sort(key=lambda l: l.outstanding)
            pool.idle[:] = live
            allowed = self._allowed_depth(pool)
            i = 0
            while pending and i < len(pool.idle):
                lease = pool.idle[i]
                if lease.outstanding >= allowed:
                    i += 1
                    continue
                kind, item, _hints = pending.popleft()
                if kind == "waiter":
                    # Waiters check the lease out exclusively.
                    pool.idle.pop(i)
                    lease.in_idle = False
                    if item.done():  # cancelled acquire; lease stays available
                        pool.idle.insert(i, lease)
                        lease.in_idle = True
                        continue
                    lease.checked_out = True
                    item.set_result(lease)
                else:
                    self._dispatch_task(key, pool, lease, item)
                    if not lease.in_idle and i < len(pool.idle) and pool.idle[i] is not lease:
                        continue  # dispatch removed it (depth cap/conn loss)
        shortfall = len(pool.pending) - pool.inflight
        while shortfall > 0 and pool.inflight < self.MAX_INFLIGHT:
            pool.inflight += 1
            shortfall -= 1
            rpc.spawn(self._request_lease(key, pool))
        # Cancel surplus in-flight requests so recycled leases don't leave
        # our own queued RequestWorkerLease RPCs pinning the raylet queue.
        surplus = pool.inflight - len(pool.pending)
        while surplus > 0 and pool.inflight_ids:
            lid = pool.inflight_ids.pop()
            surplus -= 1
            conn, msgid = pool.inflight_reqs.pop(lid, (None, None))
            if conn is not None and conn.closed:
                # The link died with the request on it: teardown already
                # failed the pending future, whose exception path does the
                # slot bookkeeping. Nothing to cancel anywhere.
                continue
            if conn is not None and conn.try_cancel_batched(msgid):
                # The request was still queued in this tick's unsent lease
                # batch: withdrawn locally, so no CancelWorkerLease may go
                # out (the raylet never saw the request; a wire cancel for
                # it would be a cancel for a phantom lease_id). The
                # awaiting coroutine observes its future cancelled and
                # exits; account for the slot here.
                pool.inflight -= 1
                continue
            try:
                (conn if conn is not None else self.core.raylet_conn).push_nowait(
                    "CancelWorkerLease", {"lease_id": lid}
                )
            except rpc.ConnectionLost:
                break

    def _lease_available(self, key, pool: _ShapePool, lease: Lease) -> None:
        """A lease (re)gained capacity: serve pending work or park it."""
        if lease.checked_out:
            return  # an acquire() waiter owns it; release() reparks it
        if lease.conn.closed:
            if lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            pool.leases.discard(lease)
            return
        if (
            pool.strategy
            and pool.strategy.get("spread")
            and lease.used
            and lease.outstanding == 0
        ):
            # SPREAD: one task per granted lease — recycling would funnel the
            # burst back onto whichever node answered first instead of the
            # round-robin placement each lease request received.
            if lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            pool.leases.discard(lease)
            rpc.spawn(self._return_worker(lease, dirty=False))
            self._pump(key, pool)
            return
        if not lease.in_idle:
            pool.idle.append(lease)
            lease.in_idle = True
        self._pump(key, pool)
        # Trim surplus idle capacity back to the raylet. Immediate return
        # only while lease requests are still in flight (a parked lease + a
        # queued request = a pinned CPU another client may be waiting on);
        # otherwise surplus leases park for a short keep-alive window so a
        # bursty submitter (trial loops, iterative drivers) reuses the full
        # worker set instead of re-leasing per burst (reference:
        # worker_lease keepalive in the direct task submitter).
        if not pool.pending and lease.in_idle and lease.outstanding == 0:
            if pool.inflight > 0:
                pool.idle.remove(lease)
                lease.in_idle = False
                pool.leases.discard(lease)
                rpc.spawn(self._return_worker(lease, dirty=False))
                return
            lease.parked_at = time.monotonic()
            self._schedule_idle_sweep(key, pool)

    def _schedule_idle_sweep(self, key, pool: _ShapePool) -> None:
        if getattr(pool, "sweep_scheduled", False):
            return
        pool.sweep_scheduled = True
        keep = config.worker_lease_idle_keep_s
        asyncio.get_running_loop().call_later(
            keep, self._sweep_idle_leases, key, pool
        )

    def _sweep_idle_leases(self, key, pool: _ShapePool) -> None:
        """Return EVERY lease parked past the keep-alive window — a parked
        lease pins its CPUs/TPUs cluster-wide (blocks other jobs and the
        autoscaler's idle scale-down), so the cache is strictly
        time-bounded."""
        pool.sweep_scheduled = False
        if pool.pending:
            return  # busy again; leases are in use
        keep = config.worker_lease_idle_keep_s
        now = time.monotonic()
        expired = [
            l
            for l in pool.idle
            if l.outstanding == 0 and now - l.parked_at >= keep
        ]
        for lease in expired:
            pool.idle.remove(lease)
            lease.in_idle = False
            pool.leases.discard(lease)
            rpc.spawn(self._return_worker(lease, dirty=False))
        if pool.idle:
            self._schedule_idle_sweep(key, pool)

    def _pool_locality_hints(self, pool: _ShapePool) -> Optional[Dict[str, float]]:
        """Aggregate arg-location hints over the next few queued work items
        so the raylet can prefer a node already holding their args. Only
        acquire() waiters carry hints (the dependency-free fast path has no
        plasma args by construction); weights count objects per holder."""
        hints: Optional[Dict[str, float]] = None
        scanned = 0
        for _kind, _item, h in pool.pending:
            if h:
                if hints is None:
                    hints = {}
                for addr_key, w in h.items():
                    hints[addr_key] = hints.get(addr_key, 0.0) + w
            scanned += 1
            if scanned >= 8:
                break
        return hints

    async def _gcs_spill_target(self, pool: _ShapePool):
        """Hop-cap fallback: per-raylet views lag under churn, and a chain of
        stale spillback suggestions can loop (A->B->A). The GCS node table is
        authoritative — pick its least-utilized ALIVE node that can ever fit
        the demand and pin the request there (spilled_from), where it queues
        until resources free instead of bouncing further."""
        try:
            reply = await self.core.gcs.call("GetAllNodes")
        except rpc.RpcError:
            return None
        demand = ResourceSet.from_units(pool.resources or {})
        best = None
        best_util = None
        for n in reply["nodes"]:
            if n.get("state") != "ALIVE":
                continue
            if not pool.pg_id and not demand.is_subset_of(
                ResourceSet.from_units(n["total"])
            ):
                # (PG demands are rewritten to group-scoped names on the
                # raylet; raw names can't be checked against totals here.)
                continue
            util = 0.0
            for k, tot in n["total"].items():
                if tot > 0 and not k.startswith("node:"):
                    util = max(util, 1.0 - n["available"].get(k, 0) / tot)
            if best_util is None or util < best_util:
                best, best_util = n, util
        return None if best is None else best["addr"]

    async def _request_lease(self, key, pool: _ShapePool) -> None:
        from ray_tpu._private.ids import fast_unique_hex

        lease_id = fast_unique_hex()
        raylet_conn = self.core.raylet_conn
        pool.inflight_ids.add(lease_id)
        locality = self._pool_locality_hints(pool)
        # The lease pump runs outside any task context, so the lease RPC
        # would leave the trace at the submit span. Borrow the trace context
        # of the first traced pending task — the request exists to serve it —
        # so the raylet's lease-lifecycle spans join that task's trace.
        for _kind, _item, _h in pool.pending:
            if _kind != "waiter" and isinstance(_item, dict) and _item.get("trace_ctx"):
                _c = _item["trace_ctx"]
                rpc._trace_ctx.set((_c["trace_id"], _c["span_id"]))
                break
        try:
            hops = 0
            used_gcs_fallback = False
            while True:
                # Batched issue: this tick's lease requests to the same
                # raylet ride one LeaseBatch frame. The msgid is recorded so
                # a cancel racing the flush withdraws the entry locally
                # (_pump) instead of sending a wire cancel for a frame that
                # never went out.
                deadline = raylet_conn._effective_deadline(None)
                fut = raylet_conn.call_batched_nowait(
                    "RequestWorkerLease",
                    {
                        "lease_id": lease_id,
                        "resources": pool.resources,
                        "pg_id": pool.pg_id,
                        "bundle_index": pool.bundle_index,
                        "strategy": pool.strategy,
                        "spilled_from": hops > 0,
                        "locality": locality,
                        # Owning job: the raylet's memory-monitor kill policy
                        # groups leased workers by owner for fair shedding.
                        "job_id": self.core.job_id,
                    },
                    deadline=deadline,
                )
                pool.inflight_reqs[lease_id] = (raylet_conn, fut.rpc_msgid)
                try:
                    reply = await raylet_conn._await_reply(fut, deadline)
                except asyncio.CancelledError:
                    if lease_id not in pool.inflight_ids:
                        # Withdrawn pre-flush by _pump's surplus trim, which
                        # already did the slot bookkeeping.
                        return
                    raise
                pool.inflight_ids.discard(lease_id)
                pool.inflight_reqs.pop(lease_id, None)
                if reply.get("cancelled"):
                    pool.inflight -= 1
                    # A cancel can cross new work: we asked to cancel this
                    # request while the queue was empty, and a task was
                    # submitted before the cancelled reply landed. Without a
                    # re-pump that task would sit pending with no request in
                    # flight, forever.
                    if pool.pending:
                        self._pump(key, pool)
                    return
                if reply.get("granted"):
                    conn = await self.core.connect_to(tuple(reply["worker_addr"]))
                    lease = Lease(
                        reply["lease_id"],
                        reply["worker_id"],
                        reply["worker_addr"],
                        conn,
                        raylet_conn,
                        fp_port=reply.get("fp_port"),
                    )
                    pool.inflight -= 1
                    pool.leases.add(lease)
                    self._lease_available(key, pool, lease)
                    return
                spill = reply.get("spillback")
                if spill is None:
                    raise rpc.RpcError(
                        f"no node can host resources {pool.resources} (cluster infeasible)"
                    )
                hops += 1
                if hops > 4:
                    if used_gcs_fallback:
                        raise rpc.RpcError(
                            "lease spillback loop exceeded 4 hops after "
                            "GCS-view fallback"
                        )
                    # Spillback chain overran its budget (stale views can
                    # bounce a request between nodes): re-anchor on the GCS
                    # global view instead of failing the task.
                    used_gcs_fallback = True
                    target_addr = await self._gcs_spill_target(pool)
                    if target_addr is None:
                        raise rpc.RpcError(
                            f"no node can host resources {pool.resources} "
                            "(cluster infeasible)"
                        )
                    hops = 1  # fresh budget; stays pinned (spilled_from)
                    raylet_conn = await self.core.connect_to(tuple(target_addr))
                    continue
                raylet_conn = await self.core.connect_to(tuple(spill["addr"]))
        except Exception as e:
            pool.inflight -= 1
            pool.inflight_ids.discard(lease_id)
            pool.inflight_reqs.pop(lease_id, None)
            # Fail one pending item (the request served one logical slot).
            while pool.pending:
                kind, item, _hints = pool.pending.popleft()
                if kind == "waiter":
                    if not item.done():
                        item.set_exception(e)
                        return
                else:
                    self.core._finish_task_error(item, e)
                    return
            return
        # unreachable: grant/cancel paths return above
        # (kept for clarity; inflight bookkeeping handled per-branch)

    # -- task dispatch over a lease (callback chain) -------------------------

    def _pool_depth(self, pool: _ShapePool) -> int:
        # SPREAD pools place per task: no pipelining, or one granted lease
        # would swallow the whole burst the strategy wants distributed.
        if pool.strategy and pool.strategy.get("spread"):
            return 1
        return self.PIPELINE_DEPTH

    def _allowed_depth(self, pool: _ShapePool) -> int:
        """Backlog-aware pipelining: pipeline deeply only when the backlog
        exceeds the lease supply. A burst of long tasks must spread over the
        leases (and spillback targets) being granted for it, not serialize
        behind the first granted worker; a deep backlog of short tasks still
        gets full-depth pipelining."""
        base = self._pool_depth(pool)
        if base == 1:
            return 1
        supply = max(1, len(pool.leases) + pool.inflight)
        backlog = len(pool.pending) + pool.total_outstanding
        return max(1, min(base, -(-backlog // supply)))

    def _dispatch_task(self, key, pool: _ShapePool, lease: Lease, wire: dict) -> None:
        """Push one task onto a lease. Caller guarantees lease.in_idle and
        capacity; this updates the capacity accounting."""
        core = self.core
        entry = core._inflight_tasks.get(wire["task_id"])
        if entry is not None and entry["cancelled"]:
            core._finish_task_error(
                wire, TaskCancelledError(f"task {wire['name']} was cancelled")
            )
            return
        if entry is not None:
            entry["conn"] = lease.conn
        core.record_task_event(wire["task_id"], wire["name"], "RUNNING")
        if (
            lease.fp_port
            and lease.fp_channel is not False
            and wire.get("args_blob") is not None
            and not wire.get("ref_positions")
            and not wire.get("kw_ref_keys")
            and wire.get("num_returns") == 1
            and "trace_ctx" not in wire
            and not wire.get("_no_fastpath")
            and not wire.get("runtime_env")  # env_vars/working_dir need the
            and not config.task_profile_events  # RPC path's application step
            and self._fp_submit(key, pool, lease, wire)
        ):
            lease.outstanding += 1
            pool.total_outstanding += 1
            lease.used = True
            if lease.outstanding >= self._pool_depth(pool) and lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            return
        try:
            # Inline reply callback (no Future/call_soon hop): the reply
            # dispatches _on_task_reply straight from the read path.
            lease.conn.call_cb(
                "PushTask",
                {"spec": wire},
                lambda r, e, k=key, p=pool, l=lease, w=wire: self._on_task_reply(
                    k, p, l, w, r, e
                ),
            )
        except rpc.ConnectionLost:
            if lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            pool.leases.discard(lease)
            rpc.spawn(self._return_worker(lease, dirty=True))
            self._retry_or_fail(key, pool, wire, rpc.ConnectionLost("worker connection lost"))
            return
        lease.outstanding += 1
        pool.total_outstanding += 1
        lease.used = True
        if lease.outstanding >= self._pool_depth(pool) and lease.in_idle:
            pool.idle.remove(lease)
            lease.in_idle = False

    # -- native fastpath (src/fastpath.cc) -----------------------------------

    def _fp_submit(self, key, pool: _ShapePool, lease: Lease, wire: dict) -> bool:
        """Hand one eligible task to the C++ direct-call channel. Returns
        False (and poisons the lease's channel) when the native path is
        unavailable, so the caller falls through to the RPC push."""
        fp = _fp_mod()
        if not fp:
            lease.fp_channel = False
            return False
        if lease.fp_channel is None:
            ch = fp.client_connect(lease.addr[0], lease.fp_port)
            if ch < 0:
                lease.fp_channel = False
                return False
            lease.fp_channel = ch
            if not self._fp_drainer_installed:
                asyncio.get_running_loop().add_reader(
                    fp.notify_fd(), self._fp_drain, fp
                )
                self._fp_drainer_installed = True
        tid = wire["task_id"]
        if not fp.submit(
            lease.fp_channel,
            tid.encode(),
            wire["func_id"].encode(),
            wire["name"].encode(),
            wire["args_blob"],
        ):
            lease.fp_channel = False
            return False
        self._fp_inflight[tid] = (key, pool, lease, wire)
        return True

    def _fp_drain(self, fp) -> None:
        """Event-loop callback: fold a batch of native completions into the
        normal reply bookkeeping (one loop wakeup per batch, not per task)."""
        for tid, status, payload in fp.drain():
            entry = self._fp_inflight.pop(tid.decode(), None)
            if entry is None:
                continue
            key, pool, lease, wire = entry
            if status == 0:  # inline value
                self._on_task_reply(
                    key, pool, lease, wire, {"returns": [{"inline": payload}]}, None
                )
            elif status == 6:  # large value parked in worker-side plasma
                import pickle

                self._on_task_reply(
                    key, pool, lease, wire,
                    {"returns": [pickle.loads(payload)]}, None,
                )
            elif status == 1:  # application error (serialized exception)
                if not payload:
                    # The C++ callback shim failed before Python could
                    # serialize anything; surface a real exception.
                    payload = serialization.serialize(
                        WorkerCrashedError("fastpath execution failed")
                    ).to_bytes()
                self._on_task_reply(
                    key, pool, lease, wire, {"error": payload}, None
                )
            elif status == 4:  # function not cached there: RPC path exports it
                lease.outstanding -= 1
                pool.total_outstanding -= 1
                wire["_no_fastpath"] = True
                pool.pending.append(("task", wire, None))
                self._lease_available(key, pool, lease)
            else:  # 2: channel lost — normal worker-death retry machinery
                lease.fp_channel = False
                self._on_task_reply(
                    key, pool, lease, wire, None, rpc._CONNECTION_LOST
                )

    def _on_task_reply(self, key, pool: _ShapePool, lease: Lease, wire: dict, reply, err) -> None:
        core = self.core
        lease.outstanding -= 1
        pool.total_outstanding -= 1
        entry = core._inflight_tasks.get(wire["task_id"])
        if entry is not None:
            entry["conn"] = None
        exc = None
        if err is not None:
            exc = (
                rpc.ConnectionLost("worker connection lost")
                if err == rpc._CONNECTION_LOST
                else rpc.RpcError(err)
            )
        if exc is None:
            core._store_task_results(wire, reply)
            if reply.get("error") is None and wire.get("actor_id") is None:
                core._register_lineage(wire, reply)
                core.record_task_event(wire["task_id"], wire["name"], "FINISHED")
            core._cleanup_task(wire)
            self._lease_available(key, pool, lease)
            return
        if isinstance(exc, rpc.ConnectionLost):
            if lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            pool.leases.discard(lease)
            if lease.outstanding == 0:
                rpc.spawn(self._return_worker(lease, dirty=True))
            if entry is not None and entry["cancelled"]:
                core._finish_task_error(
                    wire, TaskCancelledError(f"task {wire['name']} was cancelled")
                )
                return
            self._retry_or_fail(key, pool, wire, exc)
            return
        # Handler-level RpcError (worker alive): the task failed terminally.
        core._finish_task_error(wire, exc)
        self._lease_available(key, pool, lease)

    def _retry_or_fail(self, key, pool: _ShapePool, wire: dict, exc) -> None:
        core = self.core
        attempt = wire.get("_attempt", 0)
        if attempt < wire.get("max_retries", 0):
            wire["_attempt"] = attempt + 1
            core.record_task_event(
                wire["task_id"], wire["name"], "RETRY", attempt=attempt
            )
            logger.warning(
                "task %s attempt %d failed (%s); retrying", wire["name"], attempt, exc
            )
            loop = asyncio.get_running_loop()
            loop.call_later(
                min(1.0, 0.1 * (attempt + 1)),
                lambda: (pool.pending.append(("task", wire, None)), self._pump(key, pool)),
            )
        else:
            core._finish_task_error(
                wire,
                WorkerCrashedError(
                    f"task {wire['name']} failed after retries: {exc}"
                ),
            )

    # -- release / teardown --------------------------------------------------

    async def release(
        self, lease: Lease, resources, pg_id=None, bundle_index=None,
        dirty=False, strategy=None,
    ):
        key = self.shape_key(resources, pg_id, bundle_index, strategy)
        pool = self._pool(key, resources, pg_id, bundle_index, strategy)
        lease.checked_out = False
        if dirty or lease.conn.closed:
            if lease.in_idle:
                pool.idle.remove(lease)
                lease.in_idle = False
            pool.leases.discard(lease)
            await self._return_worker(lease, dirty=True)
            self._pump(key, pool)
            return
        self._lease_available(key, pool, lease)

    async def _return_worker(self, lease: Lease, dirty: bool) -> None:
        if isinstance(lease.fp_channel, int):
            fp = _fp_mod()
            if fp:
                try:
                    fp.client_close(lease.fp_channel)
                except Exception:
                    pass
            lease.fp_channel = False
        try:
            await lease.raylet_conn.call_batched(
                "ReturnWorker", {"lease_id": lease.lease_id, "dirty": dirty}
            )
        except rpc.RpcError:
            pass

    async def drain(self):
        for pool in self.pools.values():
            for lease in pool.idle:
                lease.in_idle = False
                pool.leases.discard(lease)
                await self._return_worker(lease, dirty=False)
            pool.idle.clear()


class ActorSubmitter:
    """Direct transport to one actor with per-handle sequencing and
    restart-aware redirection."""

    def __init__(self, core: "CoreWorker", actor_id: str):
        self.core = core
        self.actor_id = actor_id
        self.seq = 0
        self.conn: Optional[rpc.Connection] = None
        self.state = "PENDING"
        self.addr = None
        self.incarnation = 0
        self._lock = asyncio.Lock()
        # Count of slow-path submissions queued but not yet sent. While
        # nonzero the fast path must not cut the line (ordered actors execute
        # calls in submission order).
        self.pending_slow = 0

    async def _resolve(self, timeout: Optional[float] = None) -> None:
        if timeout is None:
            timeout = config.actor_resolve_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply = await self.core.gcs.call("GetActor", {"actor_id": self.actor_id})
            info = reply["actor"]
            if info is None:
                raise ActorDiedError(f"actor {self.actor_id[:8]} unknown to GCS")
            self.state = info["state"]
            if info["state"] == "ALIVE":
                # A restarted incarnation starts its sequence log fresh.
                if info["num_restarts"] != self.incarnation:
                    self.incarnation = info["num_restarts"]
                    self.seq = 0
                self.addr = tuple(info["addr"])
                self.conn = await self.core.connect_to(self.addr)
                return
            if info["state"] == "DEAD":
                raise ActorDiedError(
                    f"actor {self.actor_id[:8]} is dead: {info.get('death_cause')}"
                )
            await asyncio.sleep(0.1)
        raise ActorDiedError(f"timed out waiting for actor {self.actor_id[:8]} to start")

    async def submit(self, wire: dict) -> dict:
        async with self._lock:
            if self.conn is None or self.conn.closed:
                self.conn = None
                await self._resolve()
            conn = self.conn
            wire["seq_no"] = self.seq
            self.seq += 1
        try:
            return await conn.call("PushActorTask", {"spec": wire})
        except rpc.ConnectionLost:
            # Actor worker died mid-call. In-flight tasks fail (reference
            # semantics: no silent at-least-once resend); the next submit
            # re-resolves and lands on the restarted incarnation if any.
            self.conn = None
            raise ActorUnavailableError(
                f"actor {self.actor_id[:8]} died while task {wire['name']!r} was in flight"
            )


def function_id_of(pickled: bytes) -> str:
    return hashlib.blake2b(pickled, digest_size=16).hexdigest()


class CoreWorker:
    """One per process. Owns the event-loop-side runtime state."""

    def __init__(
        self,
        *,
        job_id: str,
        session_name: str,
        node_id: str,
        gcs_conn: rpc.Connection,
        raylet_conn: rpc.Connection,
        is_driver: bool,
        worker_id: str,
        server: rpc.Server,
        gcs_leader_file: Optional[str] = None,
    ):
        self.job_id = job_id
        self.session_name = session_name
        self.node_id = node_id
        resolver = None
        if gcs_leader_file:
            from ray_tpu._private import gcs_ha

            resolver = gcs_ha.file_resolver(gcs_leader_file)
        self.gcs = GcsClient(gcs_conn, resolver=resolver)
        self.raylet_conn = raylet_conn
        self.is_driver = is_driver
        self.worker_id = worker_id
        self.server = server  # shared rpc server (object server + task server)
        self.addr: Optional[Tuple[str, int]] = None  # set after server start
        self.raylet_addr: Optional[Tuple[str, int]] = None

        self.memory_store = MemoryStore()
        self.plasma = PlasmaClient(raylet_conn)
        self.reference_table = ReferenceTable()
        self.lease_pool = LeasePool(self)
        self.actor_submitters: Dict[str, ActorSubmitter] = {}
        self._conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._func_ids_exported: set = set()
        # Bounded (reference: task_event_buffer max buffer size): under
        # sustained 10k+ tasks/s the ring drops oldest events rather than
        # growing the 1 Hz GCS flush without bound.
        import collections as _collections

        self._task_events: "deque" = _collections.deque(
            maxlen=config.task_events_max_buffer
        )
        self._free_queue: List[str] = []
        self._release_queue: List[str] = []
        # Single-hold releases from value finalizers; appended from whatever
        # thread runs GC (list.append is atomic), drained by the flush loop.
        self._release_one_queue: List[str] = []
        # task_id -> {"cancelled": bool, "conn": live worker conn or None}
        self._inflight_tasks: Dict[str, dict] = {}
        self._oid_to_task: Dict[str, str] = {}
        # Streaming-generator state per producing task (reference:
        # TryReadObjectRefStream): items land here as GeneratorItem pushes
        # arrive; "done" carries the final count from the task reply.
        self._dyn_streams: Dict[str, dict] = {}
        self._oid_to_dyn: Dict[str, str] = {}
        # Lineage: oid -> {"wire": producing TaskSpec wire, "attempts": int,
        # "nbytes": retained-spec size estimate}. Lost plasma-resident task
        # returns are recomputed by re-running the producing task (reference:
        # object_recovery_manager.h:41 + task_manager.cc; deterministic
        # return ids from ids.py make the recomputed object land under the
        # same id). Ordered: total retained bytes are bounded by
        # config.lineage_bytes_limit with least-recently-registered/used
        # eviction (reference: lineage_pinning / TaskManager lineage bytes
        # accounting), so a long-lived driver cannot leak every spec it ever
        # submitted.
        self.lineage: "OrderedDict[str, dict]" = OrderedDict()
        self._lineage_bytes = 0
        # Oids whose lineage fell to the byte cap (NOT freed): recovery of
        # these raises the typed pruned error instead of the generic
        # "no lineage", so callers can tell a tuning problem from an
        # unreconstructable-by-design object.
        self._lineage_pruned: set = set()
        self._recovering: Dict[str, asyncio.Future] = {}
        self.closed = False
        self._bg_tasks: List[asyncio.Task] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._flush_wake = False
        # Cross-thread submission buffer: .remote() fast paths (any thread)
        # append wire specs here and schedule ONE loop wakeup per burst —
        # call_soon_threadsafe per call costs more than the submission itself.
        self._submit_buf: deque = deque()
        self._submit_wake = False

        server.register("GetObject", self._handle_get_object)
        server.register("GeneratorItem", self._handle_generator_item)
        server.register("DynNext", self._handle_dyn_next)
        server.register("WaitObject", self._handle_wait_object)
        server.register("RecoverObject", self._handle_recover_object)
        server.register("Ping", self._handle_ping)

    def start_background(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._bg_tasks.append(rpc.spawn(self._flush_loop()))
        # Owner-side node-death watch: when a node dies, every owned plasma
        # object whose primary copy lived there is gone — kick lineage
        # reconstruction eagerly instead of waiting for the next get to trip
        # over the dead address (reference: object_recovery_manager +
        # WaitForObjectEviction node-death subscription).
        self._bg_tasks.append(rpc.spawn(self._watch_node_deaths()))
        # Periodic runtime-telemetry flush to the GCS aggregate. Idempotent
        # per process: in an in-process cluster the driver's CoreWorker wins
        # and the shared registry flushes once.
        telemetry.start_flusher(self.gcs.call, self.worker_id, self.node_id)
        # Same deal for the runtime-span buffer (no-op when tracing is off).
        from ray_tpu.util import tracing

        tracing.start_span_flusher(self.gcs.call, self.worker_id, self.node_id)

    async def _flush_loop(self) -> None:
        while not self.closed:
            await asyncio.sleep(1.0)
            await self._flush_free_queue()
            await self._flush_release_queue()
            await self._flush_release_one_queue()
            await self._flush_task_events()

    def _wake_flush(self) -> None:
        """Prompt (debounced) free/release flush. Dropping a large object's
        last ref must recycle its arena span quickly — the span's pages are
        already faulted in, so reusing them keeps big puts off the kernel's
        first-touch page-allocation path."""
        if self._flush_wake or self._loop is None:
            return
        self._flush_wake = True
        try:
            self._loop.call_soon_threadsafe(self._start_prompt_flush)
        except RuntimeError:
            self._flush_wake = False

    def _start_prompt_flush(self) -> None:
        self._flush_wake = False
        if not self.closed:
            rpc.spawn(self._flush_frees_once())

    async def _flush_frees_once(self) -> None:
        await self._flush_free_queue()
        await self._flush_release_queue()
        await self._flush_release_one_queue()

    async def _flush_release_queue(self) -> None:
        if not self._release_queue:
            return
        oids, self._release_queue = self._release_queue, []
        await self.plasma.release_many(oids)

    async def _flush_release_one_queue(self) -> None:
        if not self._release_one_queue:
            return
        oids, self._release_one_queue = self._release_one_queue, []
        counts: Dict[str, int] = {}
        for oid in oids:
            counts[oid] = counts.get(oid, 0) + 1
        await self.plasma.release_counts(counts)

    async def _flush_free_queue(self) -> None:
        if not self._free_queue:
            return
        oids, self._free_queue = self._free_queue, []
        to_delete_local = []
        for oid in oids:
            entry = self.memory_store.get(oid)
            self.memory_store.delete(oid)
            if entry is not None and entry.kind == IN_PLASMA:
                if entry.plasma_addr == self.raylet_addr:
                    to_delete_local.append(oid)
                else:
                    rpc.spawn(self._delete_remote(oid, entry.plasma_addr))
        if to_delete_local:
            try:
                await self.plasma.delete(to_delete_local)
            except rpc.RpcError:
                pass

    async def _delete_remote(self, oid: str, addr) -> None:
        try:
            conn = await self.connect_to(tuple(addr))
            await conn.call("ObjDelete", {"oids": [oid]})
        except rpc.RpcError:
            pass

    async def _flush_task_events(self) -> None:
        if not self._task_events:
            return
        # Drain with popleft: producers append from other threads (worker
        # exec thread records PROFILE events), so list()+clear() would drop
        # anything appended between the snapshot and the clear.
        events = []
        try:
            while True:
                events.append(self._task_events.popleft())
        except IndexError:
            pass
        # Expand the hot-path tuples into wire dicts at flush time (the
        # constant per-process fields are added once here, not per event).
        out = []
        for task_id, name, state, ts, extra in events:
            ev = {
                "task_id": task_id,
                "name": name,
                "state": state,
                "job_id": self.job_id,
                "worker_id": self.worker_id,
                "node_id": self.node_id,
                "time": ts,
            }
            if extra:
                ev.update(extra)
            out.append(ev)
        try:
            await self.gcs.call("AddTaskEvents", {"events": out})
        except rpc.RpcError:
            pass

    def record_task_event(self, task_id: str, name: str, state: str, **extra) -> None:
        self._task_events.append((task_id, name, state, time.time(), extra or None))

    def schedule_free(self, oid: str) -> None:
        self._free_queue.append(oid)
        self._drop_lineage(oid)
        self._lineage_pruned.discard(oid)
        dyn_task = self._oid_to_dyn.pop(oid, None)
        if dyn_task is not None:
            self._dyn_streams.pop(dyn_task, None)
        self._wake_flush()

    def schedule_release(self, oid: str) -> None:
        self._release_queue.append(oid)
        self._wake_flush()

    async def connect_to(self, addr: Tuple[str, int]) -> rpc.Connection:
        addr = tuple(addr)
        conn = self._conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(
                *addr,
                handlers=self.server._handlers,
                sync_handlers=self.server._sync_handlers,
            )
            self._conns[addr] = conn
        return conn

    # ------------------------------------------------------------------ put

    async def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random().hex()
        await self.put_with_id(oid, value)
        ref = ObjectRef(oid, self.addr, self)
        self.reference_table.mark_owned(oid)
        return ref

    async def put_with_id(self, oid: str, value: Any) -> None:
        serialized = serialization.serialize(value)
        if serialized.total_size <= config.max_direct_call_object_size:
            self.memory_store.put_inline(oid, serialized.to_bytes())
        else:
            await self.plasma.put_serialized(oid, serialized)
            self.memory_store.put_plasma_marker(oid, self.raylet_addr)

    # ------------------------------------------------------------------ get

    async def get_objects(self, refs: List[ObjectRef], timeout: Optional[float]) -> List[Any]:
        single = False
        if isinstance(refs, ObjectRef):
            refs, single = [refs], True
        deadline = time.monotonic() + timeout if timeout is not None else None
        # Fast path: inline values already in the memory store resolve
        # synchronously — no gather Task per ref (matters when getting
        # thousands of mostly-completed refs).
        payloads = [None] * len(refs)
        pending_idx = []
        mget = self.memory_store.get
        for i, r in enumerate(refs):
            entry = mget(r.hex())
            if entry is not None and entry.kind == INLINE:
                payloads[i] = entry.payload
            else:
                pending_idx.append(i)
        if pending_idx:
            fetched = await asyncio.gather(
                *(self._resolve_payload(refs[i], deadline) for i in pending_idx)
            )
            for i, p in zip(pending_idx, fetched):
                payloads[i] = p
        values = []
        with serialization.DeserializationContext(
            ref_deserializer=self._deserialize_ref
        ):
            for ref, payload in zip(refs, payloads):
                value, is_exc = serialization.deserialize(payload)
                if is_exc:
                    raise value
                if isinstance(payload, memoryview):
                    # Plasma-backed zero-copy value: transfer one hold to the
                    # value's lifetime so the arena bytes stay mapped while
                    # the value is alive but can be spilled/evicted once it's
                    # garbage collected, even if the ObjectRef lives on
                    # (reference: plasma client buffer refcounts).
                    self._attach_value_hold(ref.hex(), value)
                values.append(value)
        return values[0] if single else values

    def _queue_release_one(self, oid: str) -> None:
        # Bound method (not list.append) so finalizers always reach the
        # *current* queue — the flush loop swaps the list object out.
        self._release_one_queue.append(oid)
        self._wake_flush()

    def _attach_value_hold(self, oid: str, value: Any) -> None:
        import weakref

        try:
            weakref.finalize(value, self._queue_release_one, oid)
        except TypeError:
            # Not weakref-able (plain containers/scalars): the hold stays
            # tied to the ObjectRef lifetime (conservative; no corruption,
            # but such objects cannot be spilled while referenced).
            pass

    def _deserialize_ref(self, hex_id, owner_addr):
        return ObjectRef(hex_id, owner_addr, self)

    def _remaining(self, deadline) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise GetTimeoutError("get timed out")
        return rem

    async def _resolve_payload(
        self, ref: ObjectRef, deadline, purpose: str = "get"
    ) -> bytes:
        oid = ref.hex()
        entry = self.memory_store.get(oid)
        owned = oid in self.reference_table.entries and self.reference_table.entries[oid].owned
        if entry is None and owned:
            entry = await self.memory_store.wait_for(oid, self._remaining(deadline))
            if entry is None:
                raise GetTimeoutError(f"timed out waiting for {oid[:12]}")
        if entry is not None:
            if entry.kind == INLINE:
                return entry.payload
            recoveries = 0
            while True:
                try:
                    return await self._fetch_plasma(
                        oid, entry.plasma_addr, deadline, purpose
                    )
                except (ObjectLostError, rpc.RpcError):
                    # Primary copy gone (node death, eviction). If we own it
                    # and have lineage, recompute; else propagate.
                    if not owned or recoveries >= config.max_lineage_reconstruction:
                        raise
                    recoveries += 1
                    await self.recover_object(oid)
                    entry = self.memory_store.get(oid)
                    if entry is None:
                        raise ObjectLostError(
                            f"object {oid[:12]} lost and reconstruction "
                            "produced no value"
                        )
                    if entry.kind == INLINE:
                        return entry.payload
        # Borrowed ref: try local plasma first (common when the primary copy
        # is on our node), else ask the owner.
        found, _ = await self.plasma.get([oid], block=False)
        if oid in found:
            return found[oid]
        return await self._fetch_from_owner(ref, deadline)

    async def _fetch_plasma(
        self, oid: str, plasma_addr, deadline, purpose: str = "get"
    ) -> memoryview:
        if tuple(plasma_addr) == self.raylet_addr:
            found, missing = await self.plasma.get([oid], timeout=self._remaining(deadline))
            if oid in found:
                return found[oid]
            raise ObjectLostError(f"object {oid[:12]} lost from local store")
        return await self.plasma.pull(oid, tuple(plasma_addr), purpose)

    async def _fetch_from_owner(self, ref: ObjectRef, deadline) -> bytes:
        if ref.owner_addr is None:
            raise ObjectLostError(f"no owner known for {ref.hex()[:12]}")
        if tuple(ref.owner_addr) == self.addr:
            # We are the owner but have no entry: freed or never created.
            raise ObjectLostError(f"object {ref.hex()[:12]} no longer exists on owner")
        try:
            conn = await self.connect_to(ref.owner_addr)
            reply = await conn.call(
                "GetObject",
                {"oid": ref.hex(), "timeout": self._remaining(deadline)},
                timeout=None,
            )
        except rpc.ConnectionLost as e:
            # The owner process is gone; with it goes the object's directory
            # entry and any lineage (reference: OwnerDiedError).
            raise ObjectLostError(
                f"owner of {ref.hex()[:12]} at {tuple(ref.owner_addr)} is "
                f"unreachable ({e}); object cannot be recovered"
            ) from e
        for _ in range(config.max_lineage_reconstruction + 1):
            status = reply.get("status")
            if status == "inline":
                return reply["payload"]
            if status == "plasma":
                try:
                    return await self._fetch_plasma(
                        ref.hex(), tuple(reply["addr"]), deadline
                    )
                except (ObjectLostError, rpc.RpcError):
                    # Primary copy unreachable; ask the owner to recover it
                    # (lineage re-execution on the owner side) and retry with
                    # the fresh location.
                    try:
                        reply = await conn.call(
                            "RecoverObject",
                            {"oid": ref.hex(), "timeout": self._remaining(deadline)},
                            timeout=None,
                        )
                    except rpc.ConnectionLost as e:
                        raise ObjectLostError(
                            f"owner of {ref.hex()[:12]} died during recovery "
                            f"({e}); object cannot be recovered"
                        ) from e
                    continue
            if status == "timeout":
                raise GetTimeoutError(f"owner timed out resolving {ref.hex()[:12]}")
            err_cls = (
                ObjectReconstructionFailedError
                if reply.get("reconstruction_failed")
                else ObjectLostError
            )
            raise err_cls(
                f"owner reports {ref.hex()[:12]}: {status}"
                + (f" ({reply['error']})" if reply.get("error") else "")
            )
        raise ObjectLostError(
            f"object {ref.hex()[:12]} unrecoverable after repeated owner recovery"
        )

    # -- owner-side object server -------------------------------------------

    async def _handle_get_object(self, conn, p):
        entry = await self.memory_store.wait_for(p["oid"], p.get("timeout", 300))
        if entry is None:
            known = p["oid"] in self.reference_table.entries
            return {"status": "timeout" if known else "unknown"}
        if entry.kind == INLINE:
            return {"status": "inline", "payload": entry.payload}
        return {"status": "plasma", "addr": list(entry.plasma_addr)}

    async def _handle_wait_object(self, conn, p):
        entry = await self.memory_store.wait_for(p["oid"], p.get("timeout"))
        return {"ready": entry is not None}

    async def _handle_recover_object(self, conn, p):
        """Borrower reports our object's primary copy lost; reconstruct via
        lineage and reply with the fresh location."""
        oid = p["oid"]
        try:
            await self.recover_object(oid)
        except ObjectReconstructionFailedError as e:
            # Typed flag so the borrower re-raises the reconstruction error
            # class, not the generic loss (callers branch on it to decide
            # between re-submitting work and failing the pipeline).
            return {"status": "lost", "error": str(e), "reconstruction_failed": True}
        except ObjectLostError as e:
            return {"status": "lost", "error": str(e)}
        entry = await self.memory_store.wait_for(oid, p.get("timeout") or 300)
        if entry is None:
            return {"status": "timeout"}
        if entry.kind == INLINE:
            return {"status": "inline", "payload": entry.payload}
        return {"status": "plasma", "addr": list(entry.plasma_addr)}

    # ---------------------------------------------- streaming generators

    def _dyn_stream(self, task_id: str) -> dict:
        st = self._dyn_streams.get(task_id)
        if st is None:
            st = self._dyn_streams[task_id] = {"items": {}, "done": None, "waiters": []}
        return st

    @staticmethod
    def _dyn_wake(st: dict) -> None:
        for w in st["waiters"]:
            if not w.done():
                w.set_result(None)
        st["waiters"].clear()

    def _dyn_item_oid(self, task_id: str, i: int) -> str:
        return deterministic_object_id(TaskID.from_hex(task_id), i + 1).hex()

    def _dyn_fail(self, task_id: str, error_payload: bytes) -> None:
        """Terminate a stream on producer failure: items not yet produced
        resolve to the task's error (consumers must not hang)."""
        st = self._dyn_stream(task_id)
        st["failed"] = error_payload
        self._dyn_wake(st)

    def _dyn_publish(self, task_id: str, total=None) -> None:
        """Publish the task's return value as a streaming generator so
        consumers start iterating while the producer still runs."""
        rid = return_object_ids(task_id, 1)[0]
        self._oid_to_dyn[rid] = task_id
        if total is None and self.memory_store.contains(rid):
            return
        gen = ObjectRefGenerator(task_id=task_id, owner_addr=self.addr, total=total)
        self.memory_store.put_inline(rid, serialization.serialize(gen).to_bytes())

    async def _handle_generator_item(self, conn, p):
        """One streamed item from the producing worker (reference:
        ReportGeneratorItemReturns, core_worker.proto)."""
        task_id, idx, ret = p["task_id"], p["index"], p["ret"]
        st = self._dyn_stream(task_id)
        oid = self._dyn_item_oid(task_id, idx)
        if "inline" in ret:
            self.memory_store.put_inline(oid, ret["inline"])
        else:
            self.memory_store.put_plasma_marker(oid, tuple(ret["plasma"]))
        self.reference_table.mark_owned(oid)
        st["items"][idx] = {k: v for k, v in ret.items() if k != "inline"} or {"inline": True}
        self._dyn_publish(task_id)
        self._dyn_wake(st)
        return {"ok": True}

    async def dyn_next(self, task_id: str, owner_addr, i: int):
        """Blocking read of stream item i; None when the stream ends first."""
        if owner_addr is None or tuple(owner_addr) == self.addr:
            st = self._dyn_stream(task_id)
            while True:
                oid = self._dyn_item_oid(task_id, i)
                if (
                    i in st["items"]
                    or (st["done"] is not None and i < st["done"])
                    or self.memory_store.contains(oid)
                ):
                    return ObjectRef(oid, self.addr, self)
                if st.get("failed") is not None:
                    # Surface the producer's error through the item ref.
                    self.memory_store.put_inline(oid, st["failed"])
                    return ObjectRef(oid, self.addr, self)
                if st["done"] is not None:
                    return None
                fut = asyncio.get_running_loop().create_future()
                st["waiters"].append(fut)
                await fut
        conn = await self.connect_to(tuple(owner_addr))
        while True:
            reply = await conn.call(
                "DynNext", {"task_id": task_id, "index": i, "timeout": 10}
            )
            if reply.get("pending"):
                continue
            if reply.get("gone"):
                raise ObjectLostError(
                    f"generator stream {task_id[:12]} is gone (freed by owner)"
                )
            if reply.get("done"):
                return None
            return ObjectRef(reply["oid"], tuple(owner_addr), self)

    async def dyn_total(self, task_id: str, owner_addr):
        if owner_addr is None or tuple(owner_addr) == self.addr:
            st = self._dyn_stream(task_id)
            while st["done"] is None:
                if st.get("failed") is not None:
                    return len(st["items"])
                fut = asyncio.get_running_loop().create_future()
                st["waiters"].append(fut)
                await fut
            return st["done"]
        conn = await self.connect_to(tuple(owner_addr))
        while True:
            reply = await conn.call("DynNext", {"task_id": task_id, "timeout": 10})
            if reply.get("pending"):
                continue
            if reply.get("gone"):
                raise ObjectLostError(
                    f"generator stream {task_id[:12]} is gone (freed by owner)"
                )
            return reply["total"]

    async def _handle_dyn_next(self, conn, p):
        """Borrower-side stream read (long-poll against the owner)."""
        task_id = p["task_id"]
        st = self._dyn_streams.get(task_id)
        if st is None:
            # No live stream state: answer from surviving objects, else the
            # stream is gone (freed or owner restarted) — do not resurrect
            # empty state that would make the borrower poll forever.
            i = p.get("index")
            if i is not None and self.memory_store.contains(
                self._dyn_item_oid(task_id, i)
            ):
                return {"oid": self._dyn_item_oid(task_id, i)}
            rid = return_object_ids(task_id, 1)[0]
            if not self.memory_store.contains(rid):
                return {"gone": True}
            st = self._dyn_stream(task_id)
        i = p.get("index")
        deadline = time.monotonic() + (p.get("timeout") or 10)
        while True:
            if i is None:
                if st["done"] is not None:
                    return {"total": st["done"]}
                if st.get("failed") is not None:
                    return {"total": len(st["items"])}
            else:
                if i in st["items"] or (st["done"] is not None and i < st["done"]):
                    return {"oid": self._dyn_item_oid(task_id, i)}
                if st.get("failed") is not None:
                    oid = self._dyn_item_oid(task_id, i)
                    self.memory_store.put_inline(oid, st["failed"])
                    return {"oid": oid}
                if st["done"] is not None:
                    return {"done": True}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"pending": True}
            fut = asyncio.get_running_loop().create_future()
            st["waiters"].append(fut)
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return {"pending": True}

    # ------------------------------------------------- lineage reconstruction

    def _register_lineage(self, wire: dict, reply: dict) -> None:
        """Remember the producing spec for every plasma-resident return so a
        lost copy can be recomputed (inline returns live in this process and
        die with the owner, at which point all refs die too)."""
        plasma_oids = []
        for oid, ret in zip(wire["return_ids"], reply.get("returns") or []):
            if "plasma" in ret:
                plasma_oids.append(oid)
        if reply.get("dynamic") is not None:
            for i, ret in enumerate(reply["dynamic"]):
                if "plasma" in ret:
                    plasma_oids.append(
                        deterministic_object_id(
                            TaskID.from_hex(wire["task_id"]), i + 1
                        ).hex()
                    )
        if reply.get("dynamic_count") is not None:
            st = self._dyn_streams.get(wire["task_id"])
            if st is not None:
                for i, ret in st["items"].items():
                    if "plasma" in ret:
                        plasma_oids.append(self._dyn_item_oid(wire["task_id"], i))
        if not plasma_oids:
            return
        # Size estimate: the spec's dominant payload is the serialized-args
        # blob; the flat overhead covers ids/resources/etc. The same wire is
        # shared by every return of the task, but charging it per return
        # keeps the accounting release-order independent (each pop subtracts
        # exactly what its insert added).
        nbytes = len(wire.get("args_blob") or b"") + 512
        for oid in plasma_oids:
            prev = self.lineage.pop(oid, None)
            if prev is not None:
                self._lineage_bytes -= prev["nbytes"]
            self._lineage_pruned.discard(oid)
            self.lineage[oid] = {
                "wire": wire,
                # A reconstruction-driven re-run must not refill the attempt
                # budget, or a flaky node makes the cap unreachable.
                "attempts": (
                    prev["attempts"]
                    if prev is not None
                    else config.max_lineage_reconstruction
                ),
                "nbytes": nbytes,
            }
            self._lineage_bytes += nbytes
        # LRU prune to the byte cap; never evict the entry just inserted
        # (a single over-cap spec must still be reconstructable once).
        while (
            self._lineage_bytes > config.lineage_bytes_limit
            and len(self.lineage) > 1
        ):
            old_oid, old = self.lineage.popitem(last=False)
            self._lineage_bytes -= old["nbytes"]
            self._lineage_pruned.add(old_oid)
        _TEL_LINEAGE_BYTES.set(self._lineage_bytes)

    def _drop_lineage(self, oid: str) -> None:
        entry = self.lineage.pop(oid, None)
        if entry is not None:
            self._lineage_bytes -= entry["nbytes"]
            _TEL_LINEAGE_BYTES.set(self._lineage_bytes)

    async def recover_object(self, oid: str, depth: int = 0) -> None:
        """Re-execute the producing task of a lost object (owner side).

        Deduplicates concurrent recoveries per producing task (one re-execution
        regenerates every return of that task). Lost owned *arguments* are
        recovered first (recursively, ``depth``-capped by
        config.reconstruction_max_depth) so the re-run's worker never fetches
        against a dead address; anything else resolves lazily because the
        re-executed task's worker pulls its args through this same get path
        (recursing borrower->owner).
        Reference: src/ray/core_worker/object_recovery_manager.h:41.
        """
        if depth > config.reconstruction_max_depth:
            _TEL_RECON_FAILED.inc()
            raise ObjectReconstructionFailedError(
                f"object {oid[:12]} lost; reconstruction recursion exceeded "
                f"reconstruction_max_depth={config.reconstruction_max_depth}"
            )
        entry = self.lineage.get(oid)
        if entry is None:
            if oid in self._lineage_pruned:
                _TEL_RECON_PRUNED.inc()
                raise ObjectReconstructionFailedError(
                    f"object {oid[:12]} lost and its producing task was "
                    f"pruned under lineage_bytes_limit="
                    f"{config.lineage_bytes_limit}; raise the limit or "
                    "persist the value outside the object store"
                )
            _TEL_RECON_FAILED.inc()
            raise ObjectReconstructionFailedError(
                f"object {oid[:12]} lost and has no lineage "
                "(ray.put objects and non-retriable actor-task returns are "
                "not reconstructable)"
            )
        # Being the subject of a recovery is an access: keep hot lineage out
        # of the prune window.
        self.lineage.move_to_end(oid)
        task_id = entry["wire"]["task_id"]
        fut = self._recovering.get(task_id)
        if fut is not None:
            # The owning recovery driver resolves this future on every path
            # (success, re-execution failure, attempts exhausted — see the
            # finally below); the get() caller owns the overall budget.
            await fut  # rpc-flow: disable=unbounded-await
            return
        if entry["attempts"] <= 0:
            _TEL_RECON_FAILED.inc()
            raise ObjectReconstructionFailedError(
                f"object {oid[:12]} lost; lineage reconstruction attempts exhausted"
            )
        entry["attempts"] -= 1
        fut = asyncio.get_running_loop().create_future()
        self._recovering[task_id] = fut
        wire = dict(entry["wire"])
        wire.pop("_attempt", None)
        logger.info(
            "reconstructing object %s by re-running task %r (depth %d)",
            oid[:12],
            wire["name"],
            depth,
        )
        self.record_task_event(wire["task_id"], wire["name"], "RECONSTRUCTING")
        t0 = time.monotonic()
        ws = time.time()
        # Re-install the submission bookkeeping that _run_task's finally
        # clause tears down.
        self._inflight_tasks[wire["task_id"]] = {"cancelled": False, "conn": None}
        for rid in wire["return_ids"]:
            self._oid_to_task[rid] = wire["task_id"]
        for dep_oid, _ in wire["dependencies"]:
            self.reference_table.add_submitted(dep_oid)
        try:
            await self._recover_lost_deps(wire, depth)
            if wire.get("actor_id"):
                # Actor-task return: resubmit through the (restarted) actor
                # (reference: task_manager.cc actor-task resubmission).
                await self._run_actor_task(wire)
            else:
                await self._run_task(wire)
            fut.set_result(None)
            _TEL_RECON_OK.inc()
            from ray_tpu.util import tracing

            tracing.record_span(
                "object.reconstruct",
                "object",
                ws,
                time.monotonic() - t0,
                oid=oid[:16],
                task=wire["name"],
                depth=depth,
            )
        except BaseException as e:
            # Typed reconstruction failures already counted their outcome at
            # the raise site (ok/pruned/failed are mutually exclusive).
            if not isinstance(e, ObjectReconstructionFailedError):
                _TEL_RECON_FAILED.inc()
            fut.set_exception(e)
            # Consume it if nobody else awaits the future.
            fut.exception()
            raise
        finally:
            self._recovering.pop(task_id, None)

    async def _recover_lost_deps(self, wire: dict, depth: int) -> None:
        """Probe the task's owned, task-produced plasma arguments and
        reconstruct any whose copy is gone (holder dead or store emptied)
        before re-running the producer. A spilled copy counts as present —
        the holder's ObjContains includes its spill table, and restore runs
        on the worker's arg fetch."""
        for dep_oid, _owner in wire.get("dependencies") or []:
            entry = self.memory_store.get(dep_oid)
            if entry is None or entry.kind != IN_PLASMA:
                continue
            if (
                dep_oid not in self.lineage
                and dep_oid not in self._lineage_pruned
            ):
                continue  # not task-produced: the pull/restore path owns it
            try:
                if tuple(entry.plasma_addr) == self.raylet_addr:
                    alive = (await self.plasma.contains([dep_oid])).get(dep_oid)
                else:
                    conn = await self.connect_to(tuple(entry.plasma_addr))
                    reply = await conn.call(
                        "ObjContains",
                        {"oids": [dep_oid]},
                        timeout=config.rpc_object_get_timeout_s,
                    )
                    alive = reply["contains"].get(dep_oid)
            except (rpc.RpcError, asyncio.TimeoutError, OSError):
                alive = False  # unreachable holder == lost copy
            if not alive:
                await self.recover_object(dep_oid, depth + 1)

    # -------------------------------------------- node-death object recovery

    async def _watch_node_deaths(self) -> None:
        try:
            await self.gcs.subscribe("nodes", self._on_node_event)
        except (rpc.RpcError, asyncio.TimeoutError, OSError) as e:
            # Standalone/degraded boots have no pubsub; loss then surfaces
            # lazily on the next get of an affected object.
            logger.debug("node-death watch unavailable: %s", e)

    def _on_node_event(self, msg) -> None:
        if not isinstance(msg, dict) or msg.get("event") != "removed":
            return
        addr = msg.get("lost_object_addr") or (msg.get("node") or {}).get("addr")
        if not addr:
            return
        dead = tuple(addr)
        if dead == self.raylet_addr:
            return  # our own raylet died; the session is going down with it
        for oid in self.memory_store.plasma_oids_at(dead):
            rpc.spawn(self._recover_lost_primary(oid))

    async def _recover_lost_primary(self, oid: str) -> None:
        try:
            await self.recover_object(oid)
        except ObjectLostError as e:
            # Unreconstructable (no lineage / pruned / exhausted): leave the
            # stale marker in place so the consumer's get raises the same
            # typed error instead of hanging on a missing entry.
            logger.warning("object %s lost to node death: %s", oid[:12], e)
        except (rpc.RpcError, asyncio.TimeoutError, OSError) as e:
            logger.warning(
                "eager reconstruction of %s failed (%s); will retry on get",
                oid[:12],
                e,
            )

    async def _handle_ping(self, conn, p):
        return {"pong": True, "worker_id": self.worker_id}

    # ------------------------------------------------------------- wait

    async def wait(
        self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        ready_flags: Dict[int, bool] = {}

        async def probe(i, ref):
            try:
                await self._wait_available(ref, None)
                ready_flags[i] = True
            except asyncio.CancelledError:
                pass

        tasks = [rpc.spawn(probe(i, r)) for i, r in enumerate(refs)]
        deadline = time.monotonic() + timeout if timeout is not None else None
        try:
            while len(ready_flags) < num_returns:
                pending = [t for t in tasks if not t.done()]
                if not pending:
                    break
                rem = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    pending, timeout=rem, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break  # timeout
        finally:
            for t in tasks:
                t.cancel()
        ready = [r for i, r in enumerate(refs) if ready_flags.get(i)]
        not_ready = [r for i, r in enumerate(refs) if not ready_flags.get(i)]
        return ready, not_ready

    async def _wait_available(self, ref: ObjectRef, timeout) -> None:
        oid = ref.hex()
        entry = self.memory_store.get(oid)
        if entry is not None:
            return
        owned = oid in self.reference_table.entries and self.reference_table.entries[oid].owned
        if owned:
            entry = await self.memory_store.wait_for(oid, timeout)
            if entry is None:
                raise GetTimeoutError(oid)
            return
        contains = await self.plasma.contains([oid])
        if contains.get(oid):
            return
        if ref.owner_addr is None or tuple(ref.owner_addr) == self.addr:
            entry = await self.memory_store.wait_for(oid, timeout)
            if entry is None:
                raise GetTimeoutError(oid)
            return
        conn = await self.connect_to(ref.owner_addr)
        await conn.call("WaitObject", {"oid": oid, "timeout": timeout}, timeout=None)

    # ----------------------------------------------------- function export

    async def export_function(self, pickled_fn: bytes) -> str:
        func_id = function_id_of(pickled_fn)
        if func_id not in self._func_ids_exported:
            await self.gcs.kv_put(func_id, pickled_fn, ns="fn", overwrite=False)
            self._func_ids_exported.add(func_id)
        return func_id

    # ------------------------------------------------------- task submission

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Serialize the call arguments; returns (blob_info, deps).

        Top-level ObjectRef args are replaced by positional markers resolved
        by the executor to values (reference semantics); nested refs pass
        through as refs. A large blob moves via the shm store.
        """
        if not args and not kwargs:
            # No-arg calls are the most common task shape; one cached blob
            # serves them all (serialize + ref-scan are ~20us per call).
            global _EMPTY_ARGS
            if _EMPTY_ARGS is None:
                _EMPTY_ARGS = serialization.serialize(([], {}))
            return _EMPTY_ARGS, [], [], []
        ref_positions = []
        plain_args = list(args)
        for i, a in enumerate(plain_args):
            if isinstance(a, ObjectRef):
                ref_positions.append(i)
        kw_ref_keys = [k for k, v in kwargs.items() if isinstance(v, ObjectRef)]
        serialized = serialization.serialize((plain_args, kwargs))
        deps = []
        for r in serialized.contained_refs:
            deps.append((r.hex(), list(r.owner_addr) if r.owner_addr else None))
        return serialized, ref_positions, kw_ref_keys, deps

    async def submit_task(
        self,
        pickled_fn: bytes,
        fn_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: Optional[int] = None,
        retry_exceptions: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        if runtime_env:
            from ray_tpu.runtime_env.context import prepare

            runtime_env = await prepare(self, runtime_env)
        if num_returns == "dynamic":
            num_returns = -1
        func_id = await self.export_function(pickled_fn)
        task_id = fast_unique_hex()
        return_ids = return_object_ids(task_id, 1 if num_returns == -1 else num_returns)
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        args_blob, args_object = None, None
        if serialized.total_size <= config.max_direct_call_object_size:
            args_blob = serialized.to_bytes()
        else:
            args_object = ObjectID.from_random().hex()
            await self.plasma.put_serialized(args_object, serialized)
            self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
            self.reference_table.mark_owned(args_object)
            self.reference_table.add_local(args_object)

        res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
        wire = self._task_wire(
            task_id=task_id,
            name=fn_name,
            func_id=func_id,
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=res.to_units(),
            max_retries=(
                max_retries if max_retries is not None else config.default_max_task_retries
            ),
            retry_exceptions=retry_exceptions,
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env,
        )
        return self._launch_task(wire)

    def _task_wire(self, *, task_id, name, func_id, args_blob, args_object,
                   ref_positions, kw_ref_keys, dependencies, num_returns,
                   return_ids, resources, max_retries=0, retry_exceptions=False,
                   pg_id=None, bundle_index=-1, scheduling_strategy=None,
                   runtime_env=None) -> dict:
        """Build a task wire dict directly (hot-path form of TaskSpec.to_wire;
        same keys, no dataclass round-trip).

        SPARSE encoding: fields at their TaskSpec defaults are omitted — all
        consumers read optional fields with .get() and TaskSpec.from_wire
        fills dataclass defaults, so the ~12 always-default actor/placement
        fields never pay msgpack pack+wire+unpack on the normal-task path
        (a few us per task at 10k tasks/s)."""
        wire = {
            "task_id": task_id,
            "job_id": self.job_id,
            "name": name,
            "func_id": func_id,
            "args_blob": args_blob,
            "dependencies": dependencies,
            "num_returns": num_returns,
            "return_ids": return_ids,
            "resources": resources,
            "max_retries": max_retries,
            "owner_addr": list(self.addr),
            "caller_id": self.worker_id,
        }
        if config.task_trace_spans or config.trace_sample_rate > 0:
            from ray_tpu.util import tracing

            ctx = tracing.make_submit_ctx(self, task_id, name)
            if ctx is not None:
                wire["trace_ctx"] = ctx
        if args_object is not None:
            wire["args_object"] = args_object
        if ref_positions:
            wire["ref_positions"] = ref_positions
        if kw_ref_keys:
            wire["kw_ref_keys"] = kw_ref_keys
        if retry_exceptions:
            wire["retry_exceptions"] = retry_exceptions
        if pg_id is not None:
            wire["pg_id"] = pg_id
            wire["bundle_index"] = bundle_index
        if scheduling_strategy is not None:
            wire["scheduling_strategy"] = scheduling_strategy
        if runtime_env is not None:
            wire["runtime_env"] = runtime_env
        return wire

    def _launch_task(self, wire: dict) -> List[ObjectRef]:
        """Register bookkeeping for a built task wire and launch it.
        Loop thread only."""
        refs = self._register_task_bookkeeping(wire)
        if wire["dependencies"]:
            rpc.spawn(self._run_task(wire))
        else:
            self.lease_pool.submit_task_fast(wire)
        return refs

    def _register_task_bookkeeping(self, wire: dict) -> List[ObjectRef]:
        return_ids = wire["return_ids"]
        self.reference_table.register_task(
            return_ids, [d for d, _ in wire["dependencies"]]
        )
        refs = [ObjectRef(oid, self.addr, self) for oid in return_ids]
        self.record_task_event(wire["task_id"], wire["name"], "PENDING")
        self._inflight_tasks[wire["task_id"]] = {"cancelled": False, "conn": None}
        oid_to_task = self._oid_to_task
        for oid in wire["return_ids"]:
            oid_to_task[oid] = wire["task_id"]
        return refs

    def try_submit_task_fast(
        self,
        pickled_fn: bytes,
        fn_name: str,
        args: tuple,
        kwargs: dict,
        *,
        loop,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: Optional[int] = None,
        retry_exceptions: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
        resources_units: Optional[Dict[str, int]] = None,
        no_fastpath: bool = False,
    ) -> Optional[List[ObjectRef]]:
        """Synchronous submission fast path, callable from any thread.

        The hot-path cost of .remote() is not the work but the thread
        round-trip into the event loop (run_coroutine_threadsafe + wait).
        Everything except launching the network I/O is thread-safe to do
        here: serialization uses thread-local context, id generation is
        random, the reference table takes a lock, and the remaining
        bookkeeping is GIL-atomic appends/inserts. Only the launch is posted
        (fire-and-forget) onto the loop. Returns None when this call needs
        the async slow path (runtime_env prep, first-time function export,
        or plasma-resident args).
        """
        if runtime_env:
            return None
        func_id = function_id_of(pickled_fn)
        if func_id not in self._func_ids_exported:
            return None  # first call pays the async export
        if num_returns == "dynamic":
            num_returns = -1
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        if serialized.total_size > config.max_direct_call_object_size:
            return None  # large args need an async plasma write
        task_id = fast_unique_hex()
        return_ids = return_object_ids(task_id, 1 if num_returns == -1 else num_returns)
        if resources_units is None:
            res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
            resources_units = res.to_units()
        wire = self._task_wire(
            task_id=task_id,
            name=fn_name,
            func_id=func_id,
            args_blob=serialized.to_bytes(),
            args_object=None,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=resources_units,
            max_retries=(
                max_retries
                if max_retries is not None
                else config.default_max_task_retries
            ),
            retry_exceptions=retry_exceptions,
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=None,
        )
        if no_fastpath:
            wire["_no_fastpath"] = True
        refs = self._register_task_bookkeeping(wire)
        self._enqueue_submit(("task", wire), loop)
        return refs

    # -- cross-thread submission funnel -------------------------------------

    def _enqueue_submit(self, item, loop) -> None:
        self._submit_buf.append(item)
        if not self._submit_wake:
            self._submit_wake = True
            loop.call_soon_threadsafe(self._drain_submit_buf)

    def _drain_submit_buf(self) -> None:
        self._submit_wake = False
        buf = self._submit_buf
        while buf:
            kind, wire = buf.popleft()
            try:
                if kind == "task":
                    if wire["dependencies"]:
                        rpc.spawn(self._run_task(wire))
                    else:
                        self.lease_pool.submit_task_fast(wire)
                else:
                    self._actor_submit_fast(wire)
            except Exception as e:
                logger.exception("fast submission of %s failed", wire.get("name"))
                self._finish_task_error(wire, e)

    async def cancel(self, ref: "ObjectRef", force: bool = False) -> bool:
        """Best-effort task cancellation (reference: ray.cancel ->
        CoreWorker::CancelTask). Queued tasks are dropped; running tasks get
        a TaskCancelledError raised in their executing thread/coroutine."""
        task_id = self._oid_to_task.get(ref.hex())
        if task_id is None:
            return False
        entry = self._inflight_tasks.get(task_id)
        if entry is None:
            return False  # already finished
        entry["cancelled"] = True
        conn = entry.get("conn")
        if conn is not None and not conn.closed:
            try:
                await conn.call(
                    "CancelTask",
                    {"task_id": task_id, "force": force},
                    timeout=config.rpc_control_timeout_s,
                )
            except rpc.RpcError:
                pass
        return True

    async def _run_task(self, wire: dict) -> None:
        task_id, name = wire["task_id"], wire["name"]
        try:
            await self._wait_for_deps(wire["dependencies"])
            attempts = wire.get("max_retries", 0) + 1
            last_err: Optional[Exception] = None
            for attempt in range(attempts):
                entry = self._inflight_tasks.get(task_id)
                if entry is not None and entry["cancelled"]:
                    self._store_task_error(
                        wire, TaskCancelledError(f"task {name} was cancelled")
                    )
                    self.record_task_event(task_id, name, "CANCELLED")
                    return
                try:
                    reply = await self._lease_and_push(wire)
                    self._store_task_results(wire, reply)
                    if reply.get("error") is None and wire.get("actor_id") is None:
                        self._register_lineage(wire, reply)
                    self.record_task_event(task_id, name, "FINISHED")
                    return
                except (rpc.ConnectionLost, WorkerCrashedError) as e:
                    last_err = e
                    entry = self._inflight_tasks.get(task_id)
                    if entry is not None and entry["cancelled"]:
                        self._store_task_error(
                            wire,
                            TaskCancelledError(f"task {name} was cancelled"),
                        )
                        return
                    self.record_task_event(task_id, name, "RETRY", attempt=attempt)
                    logger.warning(
                        "task %s attempt %d failed (%s); retrying", name, attempt, e
                    )
                    await asyncio.sleep(min(1.0, 0.1 * (attempt + 1)))
            self._store_task_error(
                wire, WorkerCrashedError(f"task {name} failed after retries: {last_err}")
            )
        except Exception as e:
            logger.exception("task %s submission failed", name)
            self._store_task_error(wire, e)
        finally:
            self._cleanup_task(wire)

    def _cleanup_task(self, wire: dict) -> None:
        self._inflight_tasks.pop(wire["task_id"], None)
        for oid in wire["return_ids"]:
            self._oid_to_task.pop(oid, None)
        for dep_oid, _ in wire["dependencies"]:
            self.reference_table.remove_submitted(dep_oid, self)

    def _finish_task_error(self, wire: dict, exc: Exception) -> None:
        """Terminal failure on the callback path: store the error and tear
        down submission bookkeeping."""
        try:
            self._store_task_error(wire, exc)
        finally:
            self._cleanup_task(wire)

    async def _wait_for_deps(self, deps) -> None:
        waits = []
        for oid, owner in deps:
            ref = ObjectRef(oid, tuple(owner) if owner else None, self)
            waits.append(self._wait_available(ref, 300))
        if waits:
            await asyncio.gather(*waits)

    def _arg_locality(self, wire: dict) -> Optional[Dict[str, float]]:
        """Locations of the task's plasma-resident args as addr-keyed
        weights ("host:port" -> object count). Deps are resolved by the time
        this runs, so the memory store knows each primary copy's raylet
        (put_plasma_marker); entries carry no sizes, so weights count
        objects, not bytes."""
        deps = wire.get("dependencies")
        if not deps:
            return None
        from ray_tpu._private.object_store import IN_PLASMA

        hints: Dict[str, float] = {}
        for oid, _owner in deps:
            entry = self.memory_store.get(oid)
            if (
                entry is not None
                and entry.kind == IN_PLASMA
                and entry.plasma_addr
            ):
                addr_key = f"{entry.plasma_addr[0]}:{entry.plasma_addr[1]}"
                hints[addr_key] = hints.get(addr_key, 0.0) + 1.0
        return hints or None

    async def _lease_and_push(self, wire: dict) -> dict:
        resources = wire.get("resources") or {}
        pg_id, bundle_index = wire.get("pg_id"), wire.get("bundle_index", -1)
        strategy = wire.get("scheduling_strategy")
        lease = await self.lease_pool.acquire(
            resources, pg_id, bundle_index, strategy,
            locality=self._arg_locality(wire),
        )
        dirty = False
        entry = None
        try:
            entry = self._inflight_tasks.get(wire["task_id"])
            if entry is not None:
                if entry["cancelled"]:
                    # Cancellation landed while we were queued for a lease.
                    raise TaskCancelledError(f"task {wire['name']} was cancelled")
                entry["conn"] = lease.conn
            self.record_task_event(wire["task_id"], wire["name"], "RUNNING")
            return await lease.conn.call("PushTask", {"spec": wire}, timeout=None)
        except rpc.ConnectionLost:
            dirty = True
            raise
        finally:
            if entry is not None:
                entry["conn"] = None
            await self.lease_pool.release(
                lease, resources, pg_id, bundle_index, dirty=dirty, strategy=strategy
            )

    def _store_task_results(self, wire: dict, reply: dict) -> None:
        if reply.get("error") is not None:
            payload = reply["error"]
            for oid in wire["return_ids"]:
                self.memory_store.put_inline(oid, payload)
            if wire.get("num_returns") == -1:
                self._dyn_fail(wire["task_id"], payload)
            self.record_task_event(wire["task_id"], wire["name"], "FAILED")
            return
        if reply.get("dynamic_count") is not None:
            # Streaming-generator task finished: items were stored as they
            # arrived (GeneratorItem pushes); record the final count and
            # publish the total-aware generator value.
            n = reply["dynamic_count"]
            task_id = wire["task_id"]
            st = self._dyn_stream(task_id)
            st["done"] = n
            for i in range(n):
                self.reference_table.mark_owned(self._dyn_item_oid(task_id, i))
            self._dyn_publish(task_id, total=n)
            self._dyn_wake(st)
            return
        if reply.get("dynamic") is not None:
            # Legacy fully-materialized generator reply: store each yielded
            # item under its deterministic id and make the main return value
            # an ObjectRefGenerator over them.
            refs = []
            for i, ret in enumerate(reply["dynamic"]):
                oid = deterministic_object_id(
                    TaskID.from_hex(wire["task_id"]), i + 1
                ).hex()
                if "inline" in ret:
                    self.memory_store.put_inline(oid, ret["inline"])
                else:
                    self.memory_store.put_plasma_marker(oid, tuple(ret["plasma"]))
                self.reference_table.mark_owned(oid)
                refs.append(ObjectRef(oid, self.addr, self))
            gen = ObjectRefGenerator(refs)
            self.memory_store.put_inline(
                wire["return_ids"][0], serialization.serialize(gen).to_bytes()
            )
            return
        returns = reply["returns"]
        put_inline = self.memory_store.put_inline
        for oid, ret in zip(wire["return_ids"], returns):
            payload = ret.get("inline")
            if payload is not None:
                put_inline(oid, payload)
            else:
                self.memory_store.put_plasma_marker(oid, tuple(ret["plasma"]))

    def _store_task_error(self, wire: dict, exc: Exception) -> None:
        serialized = serialization.serialize(exc)
        payload = serialized.to_bytes()
        for oid in wire["return_ids"]:
            self.memory_store.put_inline(oid, payload)
        if wire.get("num_returns") == -1:
            self._dyn_fail(wire["task_id"], payload)
        self.record_task_event(wire["task_id"], wire["name"], "FAILED")

    # ----------------------------------------------------------- actors

    async def create_actor(
        self,
        pickled_cls: bytes,
        cls_name: str,
        args: tuple,
        kwargs: dict,
        *,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        max_task_retries: int = 0,
        concurrency_groups: Optional[Dict[str, int]] = None,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        lifetime: Optional[str] = None,
        get_if_exists: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
        prepared_args: Optional[tuple] = None,
    ) -> str:
        if runtime_env:
            from ray_tpu.runtime_env.context import prepare

            runtime_env = await prepare(self, runtime_env)
        func_id = await self.export_function(pickled_cls)
        actor_id = ActorID.from_random().hex()
        task_id = TaskID.from_random().hex()
        strategy = dict(scheduling_strategy or {})
        if lifetime == "detached":
            strategy["detached"] = True
        res = ResourceSet(resources if resources is not None else {"CPU": 1.0})
        args_blob, args_object = None, None
        if prepared_args is not None:
            # Pre-serialized args (client proxy path: the proxy cannot
            # deserialize user values, so payloads pass through opaque).
            payload, ref_pos, kw_refs, deps = prepared_args
            if payload is None or len(payload) <= config.max_direct_call_object_size:
                args_blob = payload
            else:
                args_object = ObjectID.from_random().hex()
                await self.plasma.put_bytes(args_object, payload)
                self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        else:
            serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
            if serialized.total_size <= config.max_direct_call_object_size:
                args_blob = serialized.to_bytes()
            else:
                args_object = ObjectID.from_random().hex()
                await self.plasma.put_serialized(args_object, serialized)
                self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            name=cls_name,
            func_id=func_id,
            args_blob=args_blob,
            args_object=args_object,
            ref_positions=ref_pos,
            kw_ref_keys=kw_refs,
            dependencies=deps,
            num_returns=0,
            return_ids=[],
            resources=res.to_units(),
            owner_addr=list(self.addr),
            actor_id=actor_id,
            actor_creation=True,
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            max_task_retries=max_task_retries,
            concurrency_groups=concurrency_groups,
            pg_id=pg_id,
            bundle_index=bundle_index,
            scheduling_strategy=strategy,
            runtime_env=runtime_env,
            actor_name=name,
            namespace=namespace,
        )
        wire = spec.to_wire()
        reply = await self.gcs.call(
            "CreateActor",
            {"spec": wire, "wait_alive": False, "get_if_exists": get_if_exists},
            timeout=None,
        )
        if reply.get("existing"):
            return reply["actor"]["actor_id"]
        return actor_id

    def _submitter(self, actor_id: str) -> ActorSubmitter:
        sub = self.actor_submitters.get(actor_id)
        if sub is None:
            sub = self.actor_submitters[actor_id] = ActorSubmitter(self, actor_id)
        return sub

    def _actor_wire(
        self, actor_id, method_name, args_blob, args_object,
        ref_pos, kw_refs, deps, num_returns, return_ids, task_id,
        max_task_retries=0, concurrency_group=None,
    ) -> dict:
        wire = {
            "task_id": task_id,
            "job_id": self.job_id,
            "name": method_name,
            "func_id": "",
            "args_blob": args_blob,
            "args_object": args_object,
            "ref_positions": ref_pos,
            "kw_ref_keys": kw_refs,
            "dependencies": deps,
            "num_returns": num_returns,
            "return_ids": return_ids,
            "resources": {},
            "max_retries": max_task_retries,
            "retry_exceptions": False,
            "owner_addr": list(self.addr),
            "actor_id": actor_id,
            "actor_creation": False,
            "actor_method": method_name,
            "seq_no": -1,
            "caller_id": self.worker_id,
            "pg_id": None,
            "bundle_index": -1,
            "scheduling_strategy": None,
            "runtime_env": None,
            "concurrency_group": concurrency_group,
        }
        if config.task_trace_spans or config.trace_sample_rate > 0:
            from ray_tpu.util import tracing

            ctx = tracing.make_submit_ctx(self, task_id, method_name)
            if ctx is not None:
                wire["trace_ctx"] = ctx
        return wire

    async def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
        max_task_retries: int = 0,
        concurrency_group: Optional[str] = None,
        prepared_args: Optional[tuple] = None,
    ) -> List[ObjectRef]:
        if num_returns == "dynamic":
            num_returns = -1
        task_id = fast_unique_hex()
        # Dynamic (streaming-generator) calls have ONE return object whose
        # value is the ObjectRefGenerator (same convention as submit_task).
        return_ids = return_object_ids(
            task_id, 1 if num_returns == -1 else num_returns
        )
        args_blob, args_object = None, None
        if prepared_args is not None:
            payload, ref_pos, kw_refs, deps = prepared_args
            if payload is None or len(payload) <= config.max_direct_call_object_size:
                args_blob = payload
            else:
                args_object = ObjectID.from_random().hex()
                await self.plasma.put_bytes(args_object, payload)
                self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        else:
            serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
            if serialized.total_size <= config.max_direct_call_object_size:
                args_blob = serialized.to_bytes()
            else:
                args_object = ObjectID.from_random().hex()
                await self.plasma.put_serialized(args_object, serialized)
                self.memory_store.put_plasma_marker(args_object, self.raylet_addr)
        wire = self._actor_wire(
            actor_id, method_name, args_blob, args_object,
            ref_pos, kw_refs, deps, num_returns, return_ids, task_id,
            max_task_retries, concurrency_group,
        )
        refs = []
        for oid in return_ids:
            self.reference_table.mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        if not deps and args_object is None:
            self._actor_submit_fast(wire)
        else:
            self._spawn_actor_slow(wire)
        return refs

    def try_submit_actor_task_fast(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        loop,
        num_returns: int = 1,
        max_task_retries: int = 0,
        concurrency_group: Optional[str] = None,
    ) -> Optional[List[ObjectRef]]:
        """Synchronous actor-call fast path (see try_submit_task_fast)."""
        if num_returns == "dynamic":
            num_returns = -1
        serialized, ref_pos, kw_refs, deps = self._prepare_args(args, kwargs)
        if serialized.total_size > config.max_direct_call_object_size:
            return None
        task_id = fast_unique_hex()
        return_ids = return_object_ids(
            task_id, 1 if num_returns == -1 else num_returns
        )
        wire = self._actor_wire(
            actor_id, method_name, serialized.to_bytes(), None,
            ref_pos, kw_refs, deps, num_returns, return_ids, task_id,
            max_task_retries, concurrency_group,
        )
        refs = []
        mark_owned = self.reference_table.mark_owned
        for oid in return_ids:
            mark_owned(oid)
            refs.append(ObjectRef(oid, self.addr, self))
        for dep_oid, _ in deps:
            self.reference_table.add_submitted(dep_oid)
        self._enqueue_submit(("actor", wire), loop)
        return refs

    def _spawn_actor_slow(self, wire: dict) -> None:
        """Slow-path actor submission via coroutine (first call, restarts,
        dependencies, large args). Bumps pending_slow synchronously so fast
        submissions queued after this one cannot overtake it."""
        sub = self._submitter(wire["actor_id"])
        sub.pending_slow += 1
        rpc.spawn(self._run_actor_task(wire, sub))

    def _actor_submit_fast(self, wire: dict) -> None:
        """Callback-based actor submission (loop thread). Sends the PushActorTask
        frame directly when the submitter is in steady state; otherwise falls
        back to the coroutine path (reference: direct_actor_task_submitter's
        send-or-queue split)."""
        if wire["dependencies"]:
            self._spawn_actor_slow(wire)
            return
        sub = self._submitter(wire["actor_id"])
        conn = sub.conn
        if (
            conn is None
            or conn.closed
            or sub.pending_slow > 0
            or sub._lock.locked()
            or sub.state != "ALIVE"
        ):
            self._spawn_actor_slow(wire)
            return
        wire["seq_no"] = sub.seq
        sub.seq += 1
        try:
            # Fold the ambient deadline like Connection.call does: an actor
            # call made while serving (or routing) a deadlined request rides
            # the fast path with the same TTL stamp, so the replica-side
            # server can shed/cancel it (serve admission control relies on
            # this for the no-admitted-request-overruns guarantee).
            fut = conn.call_nowait(
                "PushActorTask",
                {"spec": wire},
                deadline=rpc.current_deadline(),
            )
        except rpc.ConnectionLost:
            sub.conn = None
            if wire.get("max_retries", 0) > wire.get("_attempt", 0):
                wire["_attempt"] = wire.get("_attempt", 0) + 1
                self._spawn_actor_slow(wire)
                return
            self._finish_task_error(
                wire,
                ActorUnavailableError(
                    f"actor {wire['actor_id'][:8]} died while task "
                    f"{wire['name']!r} was in flight"
                ),
            )
            return
        fut.add_done_callback(
            lambda f, w=wire, s=sub: self._on_actor_reply(w, s, f)
        )

    def _on_actor_reply(self, wire: dict, sub: ActorSubmitter, fut) -> None:
        exc = fut.exception() if not fut.cancelled() else rpc.ConnectionLost("cancelled")
        if exc is None:
            reply = fut.result()
            self._store_task_results(wire, reply)
            if reply.get("error") is None and wire.get("max_retries", 0) > 0:
                # Actor-task lineage: retriable methods register their
                # plasma-resident returns for reconstruction through the
                # (possibly restarted) actor (reference: task_manager.cc
                # resubmit of actor tasks with max_task_retries > 0).
                self._register_lineage(wire, reply)
            self._cleanup_task(wire)
            return
        if isinstance(exc, rpc.ConnectionLost):
            sub.conn = None
            if wire.get("max_retries", 0) > wire.get("_attempt", 0):
                # Resubmit through the slow path: it re-resolves the actor
                # (waiting out a restart) before pushing again.
                wire["_attempt"] = wire.get("_attempt", 0) + 1
                self.record_task_event(
                    wire["task_id"], wire["name"], "RETRY",
                    attempt=wire["_attempt"],
                )
                self._spawn_actor_slow(wire)
                return
            self._store_task_error(
                wire,
                ActorUnavailableError(
                    f"actor {wire['actor_id'][:8]} died while task "
                    f"{wire['name']!r} was in flight"
                ),
            )
        else:
            self._store_task_error(wire, exc)
        self._cleanup_task(wire)

    async def _run_actor_task(self, wire: dict, sub: Optional[ActorSubmitter] = None) -> None:
        if sub is None:
            sub = self._submitter(wire["actor_id"])
            sub.pending_slow += 1
        try:
            try:
                await self._wait_for_deps(wire["dependencies"])
                attempts = wire.get("max_retries", 0) + 1
                attempt = wire.get("_attempt", 0)
                while True:
                    try:
                        reply = await sub.submit(wire)
                        break
                    except (ActorUnavailableError, rpc.ConnectionLost) as e:
                        attempt += 1
                        wire["_attempt"] = attempt
                        if attempt >= attempts:
                            raise
                        self.record_task_event(
                            wire["task_id"], wire["name"], "RETRY", attempt=attempt
                        )
                        await asyncio.sleep(min(1.0, 0.2 * attempt))
            finally:
                sub.pending_slow -= 1
            self._store_task_results(wire, reply)
            if reply.get("error") is None and wire.get("max_retries", 0) > 0:
                self._register_lineage(wire, reply)
        except Exception as e:
            if isinstance(e, rpc.ConnectionLost):
                # Callers' retry loops catch RayTpuError (the documented
                # pattern); a raw transport error must not leak past them
                # when the real meaning is "the actor's process went away".
                e = ActorUnavailableError(
                    f"actor {wire['actor_id'][:8]} unreachable for task "
                    f"{wire['name']!r}: {e}"
                )
            self._store_task_error(wire, e)
        finally:
            self._cleanup_task(wire)

    async def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        await self.gcs.call("KillActor", {"actor_id": actor_id, "no_restart": no_restart})

    # ---------------------------------------------------------- shutdown

    async def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for t in self._bg_tasks:
            t.cancel()
        await self._flush_task_events()
        # Flush-on-exit for runtime spans, mirroring the task-event flush:
        # a short-lived worker's spans must not die in its local buffer.
        from ray_tpu.util import tracing

        if tracing.enabled():
            try:
                await tracing.flush_spans_once(
                    self.gcs.call, self.worker_id, self.node_id
                )
            except Exception:
                pass
        tracing.stop_flusher()  # flusher task dies with this loop
        if self.lease_pool._fp_drainer_installed:
            fp = _fp_mod()
            if fp:
                try:
                    asyncio.get_running_loop().remove_reader(fp.notify_fd())
                except Exception:
                    pass
            self.lease_pool._fp_drainer_installed = False
        await self.lease_pool.drain()
        self.plasma.close()
        for conn in self._conns.values():
            await conn.close()
