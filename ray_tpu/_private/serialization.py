"""Value serialization for the object store.

Analog of the reference's SerializationContext (python/ray/_private/serialization.py:111):
cloudpickle for arbitrary Python values, pickle protocol 5 ``buffer_callback`` for
out-of-band zero-copy of large contiguous buffers (numpy / jax host arrays), and
custom reducers for ObjectRef so refs travel inside task args and returns.

Wire layout of a stored object (one contiguous byte region, shm- and
socket-friendly)::

    [4B header_len][msgpack header][pad to 64][buffer 0][pad][buffer 1] ...

header = {"p": pickled-meta-bytes, "o": [buffer offsets], "s": [buffer sizes],
          "e": bool is_exception}

Buffers are 64-byte aligned so deserialized numpy views over shm are
cache-line aligned and directly usable by jax.numpy / dlpack without a copy.
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
from typing import Any, Callable, List, Optional, Tuple

import msgpack

import cloudpickle

_ALIGN = 64
_LEN = struct.Struct("<I")
# Buffers smaller than this are cheaper to keep inline in the pickle stream.
_OOB_THRESHOLD = 512


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _SerializationThreadContext(threading.local):
    def __init__(self):
        self.contained_refs: Optional[List[Any]] = None
        self.ref_deserializer: Optional[Callable] = None
        self.actor_handle_deserializer: Optional[Callable] = None


_ctx = _SerializationThreadContext()


def record_contained_ref(ref) -> None:
    """Called by ObjectRef.__reduce__ while a serialize() is in flight."""
    if _ctx.contained_refs is not None:
        _ctx.contained_refs.append(ref)


def get_ref_deserializer():
    return _ctx.ref_deserializer


def get_actor_handle_deserializer():
    return _ctx.actor_handle_deserializer


class SerializedObject:
    """A serialized value: header + list of out-of-band buffers.

    ``contained_refs`` lists every ObjectRef found inside the value — the
    caller uses it for distributed ref counting (the reference tracks the
    same set in CoreWorker::Put / TaskManager).
    """

    __slots__ = ("header", "buffers", "contained_refs", "is_exception")

    def __init__(self, header: bytes, buffers: List[memoryview], contained_refs, is_exception):
        self.header = header
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.is_exception = is_exception

    @property
    def total_size(self) -> int:
        size = _align(4 + len(self.header))
        for buf in self.buffers:
            size = _align(size + buf.nbytes)
        # Trailing pad is harmless; reserve exact: recompute without final pad.
        size = 4 + len(self.header)
        for buf in self.buffers:
            size = _align(size) + buf.nbytes
        return size

    def write_to(self, dest: memoryview) -> int:
        """Write the full wire layout into ``dest``; returns bytes written."""
        offset = 0
        dest[0:4] = _LEN.pack(len(self.header))
        offset = 4
        dest[offset : offset + len(self.header)] = self.header
        offset += len(self.header)
        for buf in self.buffers:
            offset = _align(offset)
            dest[offset : offset + buf.nbytes] = buf.cast("B") if buf.format != "B" or buf.ndim != 1 else buf
            offset += buf.nbytes
        return offset

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_to(memoryview(out))
        return bytes(out)


def serialize(value: Any) -> SerializedObject:
    _maybe_register_jax_reducers()
    is_exception = isinstance(value, BaseException)
    buffers: List[pickle.PickleBuffer] = []
    prev = _ctx.contained_refs
    _ctx.contained_refs = []
    try:
        def buffer_cb(pb: pickle.PickleBuffer) -> bool:
            view = pb.raw()
            if view.nbytes < _OOB_THRESHOLD:
                return True  # keep small buffers inline
            buffers.append(pb)
            return False

        meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_cb)
        contained = _ctx.contained_refs
    finally:
        _ctx.contained_refs = prev

    raw_views = [pb.raw() for pb in buffers]
    # Compute offsets for the wire layout.
    offsets: List[int] = []
    sizes: List[int] = []
    # Header must be built before offsets are final; offsets are relative to
    # the start of the whole region, so build header iteratively: header size
    # changes offsets, so instead make offsets relative to the END of the
    # header region, which is itself aligned.
    rel = 0
    for view in raw_views:
        rel = _align(rel)
        offsets.append(rel)
        sizes.append(view.nbytes)
        rel += view.nbytes
    header = msgpack.packb(
        {"p": meta, "o": offsets, "s": sizes, "e": is_exception}, use_bin_type=True
    )
    return SerializedObject(header, raw_views, contained, is_exception)


def deserialize(region) -> Tuple[Any, bool]:
    """Inverse of serialize. ``region`` is a bytes-like over the wire layout.

    Returns (value, is_exception). Out-of-band buffers are zero-copy views
    into ``region`` — the caller must keep the backing memory alive as long
    as the value is (the object store client pins it).
    """
    view = memoryview(region)
    (header_len,) = _LEN.unpack(view[0:4])
    header = msgpack.unpackb(view[4 : 4 + header_len], raw=False)
    base = _align(4 + header_len)
    # Offsets recorded relative to a zero base then shifted by aligned header.
    bufs = []
    for off, size in zip(header["o"], header["s"]):
        start = base + off
        bufs.append(view[start : start + size])
    value = pickle.loads(header["p"], buffers=bufs)
    return value, header["e"]


def header_buffer_base(region) -> int:
    view = memoryview(region)
    (header_len,) = _LEN.unpack(view[0:4])
    return _align(4 + header_len)


class DeserializationContext:
    """Installs ref/actor-handle deserializers for the current thread while
    deserializing (the worker sets this so unpickled ObjectRefs re-attach to
    the local core worker for ref counting and `get`)."""

    def __init__(self, ref_deserializer=None, actor_handle_deserializer=None):
        self._ref = ref_deserializer
        self._actor = actor_handle_deserializer

    def __enter__(self):
        self._prev = (_ctx.ref_deserializer, _ctx.actor_handle_deserializer)
        _ctx.ref_deserializer = self._ref
        _ctx.actor_handle_deserializer = self._actor
        return self

    def __exit__(self, *exc):
        _ctx.ref_deserializer, _ctx.actor_handle_deserializer = self._prev
        return False


def _rebuild_jax_array(np_val):
    return np_val


def _reduce_jax_array(arr):
    import jax
    import numpy as np

    return (_rebuild_jax_array, (np.asarray(jax.device_get(arr)),))


_jax_reducers_registered = False


def _maybe_register_jax_reducers() -> None:
    """Teach pickle to move jax.Arrays as host numpy arrays (out-of-band).

    Device arrays are fetched to host at Put time; consumers re-place them on
    device (device_put is cheap and sharding-aware). This mirrors how the
    reference moves torch tensors through plasma as host memory.

    Lazy by design: importing jax costs seconds, so registration only happens
    once user code has already imported jax into this process.
    """
    global _jax_reducers_registered
    if _jax_reducers_registered or "jax" not in sys.modules:
        return
    try:
        import copyreg

        from jax._src.array import ArrayImpl

        copyreg.pickle(ArrayImpl, _reduce_jax_array)
        _jax_reducers_registered = True
    except Exception:
        _jax_reducers_registered = True
