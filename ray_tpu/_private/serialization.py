"""Value serialization for the object store.

Analog of the reference's SerializationContext (python/ray/_private/serialization.py:111):
cloudpickle for arbitrary Python values, pickle protocol 5 ``buffer_callback`` for
out-of-band zero-copy of large contiguous buffers (numpy / jax host arrays), and
custom reducers for ObjectRef so refs travel inside task args and returns.

Wire layout of a stored object (one contiguous byte region, shm- and
socket-friendly)::

    [4B header_len][msgpack header][pad to 64][buffer 0][pad][buffer 1] ...

header = {"p": pickled-meta-bytes, "o": [buffer offsets], "s": [buffer sizes],
          "e": bool is_exception}

Buffers are 64-byte aligned so deserialized numpy views over shm are
cache-line aligned and directly usable by jax.numpy / dlpack without a copy.
"""

from __future__ import annotations

import os
import pickle
import struct
import sys
import threading
from typing import Any, Callable, List, Optional, Tuple

import msgpack

import cloudpickle

_ALIGN = 64
_LEN = struct.Struct("<I")
# Buffers smaller than this are cheaper to keep inline in the pickle stream.
_OOB_THRESHOLD = 512

try:
    from ray_tpu._native._shm import parallel_copy as _parallel_copy
except ImportError:  # pragma: no cover - pure-python installs
    _parallel_copy = None

try:
    from ray_tpu._native._shm import copy_nt as _copy_nt
except ImportError:  # pragma: no cover - pure-python installs
    _copy_nt = None

# copy_nt only beats a slice assign once the destination stops fitting in
# cache (its non-temporal path engages at 1 MiB; below that it is a plain
# memcpy behind an extra call).
_NT_MIN = 1 << 20

# Threads for the GIL-released multithreaded memcpy. On few-core hosts the
# fan-out/join overhead plus contention makes it SLOWER than one plain slice
# copy (measured: 1.2-1.8ms vs 0.72ms per 16 MiB on 1 core), so it only
# engages when enough cores exist to win.
_COPY_THREADS = min(4, os.cpu_count() or 1)
if _COPY_THREADS < 3:
    _parallel_copy = None


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _SerializationThreadContext(threading.local):
    def __init__(self):
        self.contained_refs: Optional[List[Any]] = None
        self.ref_deserializer: Optional[Callable] = None
        self.actor_handle_deserializer: Optional[Callable] = None


_ctx = _SerializationThreadContext()


def record_contained_ref(ref) -> None:
    """Called by ObjectRef.__reduce__ while a serialize() is in flight."""
    if _ctx.contained_refs is not None:
        _ctx.contained_refs.append(ref)


def get_ref_deserializer():
    return _ctx.ref_deserializer


def get_actor_handle_deserializer():
    return _ctx.actor_handle_deserializer


class SerializedObject:
    """A serialized value: header + list of out-of-band buffers.

    ``contained_refs`` lists every ObjectRef found inside the value — the
    caller uses it for distributed ref counting (the reference tracks the
    same set in CoreWorker::Put / TaskManager).
    """

    __slots__ = ("header", "buffers", "contained_refs", "is_exception", "_size")

    def __init__(self, header: bytes, buffers: List[memoryview], contained_refs, is_exception):
        self.header = header
        self.buffers = buffers
        self.contained_refs = contained_refs
        self.is_exception = is_exception
        self._size = None

    @property
    def total_size(self) -> int:
        if self._size is None:
            size = 4 + len(self.header)
            for buf in self.buffers:
                size = _align(size) + buf.nbytes
            self._size = size
        return self._size

    def write_to(self, dest: memoryview) -> int:
        """Write the full wire layout into ``dest``; returns bytes written."""
        offset = 0
        dest[0:4] = _LEN.pack(len(self.header))
        offset = 4
        dest[offset : offset + len(self.header)] = self.header
        offset += len(self.header)
        for buf in self.buffers:
            offset = _align(offset)
            flat = buf.cast("B") if buf.format != "B" or buf.ndim != 1 else buf
            n = flat.nbytes
            if n >= (4 << 20) and _parallel_copy is not None:
                # Multithreaded GIL-released memcpy (src/shm_buffer.cc):
                # large puts run at memory bandwidth, not one core's memcpy.
                _parallel_copy(dest[offset : offset + n], flat, _COPY_THREADS)
            elif n >= _NT_MIN and _copy_nt is not None:
                # Single-threaded cache-bypassing copy: shm destinations are
                # cold, so streaming stores skip the read-for-ownership that
                # dominates a regular large memcpy.
                _copy_nt(dest[offset : offset + n], flat)
            else:
                dest[offset : offset + n] = flat
            offset += n
        return offset

    def to_bytes(self) -> "bytes | bytearray":
        """The serialized region as one contiguous buffer. Returns the
        ``bytearray`` it was built into when out-of-band buffers are present
        — a final ``bytes(out)`` would copy the whole region again. Callers
        treat the result as read-only; anything crossing into native code
        that requires exact ``bytes`` (the fastpath channel's
        PyBytes_AsStringAndSize) must wrap it itself."""
        if not self.buffers:
            # Hot path: no out-of-band buffers — the region is just the
            # length-prefixed header.
            return _LEN.pack(len(self.header)) + self.header
        out = bytearray(self.total_size)
        self.write_to(memoryview(out))
        return out


_SIMPLE_SCALARS = (type(None), bool, int, float, str, bytes)


def _is_simple(value: Any, depth: int = 3) -> bool:
    """True when plain (C) pickle provably round-trips ``value`` with the
    same semantics as cloudpickle: scalars, numpy arrays, ObjectRefs (custom
    __reduce__), and shallow containers of those. Anything else — functions,
    classes, arbitrary instances — may pickle by module reference (wrong for
    __main__-defined objects), so it takes the cloudpickle path."""
    t = type(value)
    if t in _SIMPLE_SCALARS:
        return True
    name = t.__name__
    if name == "ndarray" and t.__module__ == "numpy":
        # object-dtype arrays can hold cloudpickle-only values.
        return not value.dtype.hasobject
    if (
        name in ("ObjectRef", "ActorHandle", "ClientObjectRef")
        and t.__module__.startswith("ray_tpu")
    ):
        return True
    if depth > 0:
        if t is tuple or t is list:
            if len(value) <= 16:
                return all(_is_simple(v, depth - 1) for v in value)
            return False
        if t is dict:
            if len(value) <= 16:
                return all(
                    _is_simple(k, depth - 1) and _is_simple(v, depth - 1)
                    for k, v in value.items()
                )
            return False
    return False


def serialize(value: Any) -> SerializedObject:
    _maybe_register_jax_reducers()
    is_exception = isinstance(value, BaseException)
    buffers: List[pickle.PickleBuffer] = []
    prev = _ctx.contained_refs
    _ctx.contained_refs = []
    try:
        def buffer_cb(pb: pickle.PickleBuffer) -> bool:
            view = pb.raw()
            if view.nbytes < _OOB_THRESHOLD:
                return True  # keep small buffers inline
            buffers.append(pb)
            return False

        # C-pickle fast path for provably-safe values (~10x cheaper than
        # building a CloudPickler); cloudpickle for everything else.
        if _is_simple(value):
            meta = pickle.dumps(value, protocol=5, buffer_callback=buffer_cb)
        else:
            meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_cb)
        contained = _ctx.contained_refs
    finally:
        _ctx.contained_refs = prev

    raw_views = [pb.raw() for pb in buffers]
    # Compute offsets for the wire layout.
    offsets: List[int] = []
    sizes: List[int] = []
    # Header must be built before offsets are final; offsets are relative to
    # the start of the whole region, so build header iteratively: header size
    # changes offsets, so instead make offsets relative to the END of the
    # header region, which is itself aligned.
    rel = 0
    for view in raw_views:
        rel = _align(rel)
        offsets.append(rel)
        sizes.append(view.nbytes)
        rel += view.nbytes
    header = msgpack.packb(
        {"p": meta, "o": offsets, "s": sizes, "e": is_exception}, use_bin_type=True
    )
    return SerializedObject(header, raw_views, contained, is_exception)


def deserialize(region) -> Tuple[Any, bool]:
    """Inverse of serialize. ``region`` is a bytes-like over the wire layout.

    Returns (value, is_exception). Out-of-band buffers are zero-copy views
    into ``region`` — the caller must keep the backing memory alive as long
    as the value is (the object store client pins it).
    """
    view = memoryview(region)
    (header_len,) = _LEN.unpack(view[0:4])
    header = msgpack.unpackb(view[4 : 4 + header_len], raw=False)
    base = _align(4 + header_len)
    # Offsets recorded relative to a zero base then shifted by aligned header.
    bufs = []
    for off, size in zip(header["o"], header["s"]):
        start = base + off
        bufs.append(view[start : start + size])
    value = pickle.loads(header["p"], buffers=bufs)
    return value, header["e"]


def header_buffer_base(region) -> int:
    view = memoryview(region)
    (header_len,) = _LEN.unpack(view[0:4])
    return _align(4 + header_len)


class DeserializationContext:
    """Installs ref/actor-handle deserializers for the current thread while
    deserializing (the worker sets this so unpickled ObjectRefs re-attach to
    the local core worker for ref counting and `get`)."""

    def __init__(self, ref_deserializer=None, actor_handle_deserializer=None):
        self._ref = ref_deserializer
        self._actor = actor_handle_deserializer

    def __enter__(self):
        self._prev = (_ctx.ref_deserializer, _ctx.actor_handle_deserializer)
        _ctx.ref_deserializer = self._ref
        _ctx.actor_handle_deserializer = self._actor
        return self

    def __exit__(self, *exc):
        _ctx.ref_deserializer, _ctx.actor_handle_deserializer = self._prev
        return False


def _rebuild_jax_array(np_val):
    return np_val


def _reduce_jax_array(arr):
    import jax
    import numpy as np

    return (_rebuild_jax_array, (np.asarray(jax.device_get(arr)),))


_jax_reducers_registered = False


def _maybe_register_jax_reducers() -> None:
    """Teach pickle to move jax.Arrays as host numpy arrays (out-of-band).

    Device arrays are fetched to host at Put time; consumers re-place them on
    device (device_put is cheap and sharding-aware). This mirrors how the
    reference moves torch tensors through plasma as host memory.

    Lazy by design: importing jax costs seconds, so registration only happens
    once user code has already imported jax into this process.
    """
    global _jax_reducers_registered
    if _jax_reducers_registered or "jax" not in sys.modules:
        return
    try:
        import copyreg

        from jax._src.array import ArrayImpl

        copyreg.pickle(ArrayImpl, _reduce_jax_array)
        _jax_reducers_registered = True
    except Exception:
        _jax_reducers_registered = True
