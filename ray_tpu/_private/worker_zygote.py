"""Worker zygote: fork preloaded worker processes in milliseconds.

TPU-native analog of the reference worker pool's prestart machinery
(src/ray/raylet/worker_pool.cc PrestartWorkers / maximum_startup_concurrency):
instead of paying a cold `python -m worker_main` exec + import (~0.5-1.5s)
per worker, the raylet keeps ONE zygote process that has already imported
the worker stack; each worker is an os.fork() of it (~10ms, copy-on-write
imports). At 1000 actors on a small host this is the difference between
minutes of spawn wall and seconds.

Protocol (two unix-domain socketpairs, one JSON line per message; separate
request and response channels so the raylet's asyncio reader — which sets
O_NONBLOCK on its file description — can never flip the raylet's blocking
request sends into EAGAIN mid-message):
    requests  (raylet -> zygote): {"env": {...}} + [stdout_fd, stderr_fd]
                                  via SCM_RIGHTS
    responses (zygote -> raylet): {"forked": pid}
                                  {"exit": pid, "code": n}  (zygote reaps)

The zygote is fork-safe by construction: a single-threaded, loop-free
process that only blocks in recvmsg. Forked children dup2 the passed fds
onto stdout/stderr (the raylet's per-worker log pump reads the pipe read
ends exactly as it does for exec'd workers) and enter worker_main's main()
fresh — no inherited event loop, no inherited threads.
"""

from __future__ import annotations

import array
import json
import os
import signal
import socket
import sys


_TIMEOUT = object()  # sentinel: no message arrived within the poll window


def _recv_msg(sock: socket.socket):
    """One JSON line + up to 2 fds. Returns (obj, fds), (None, []) on EOF,
    or (_TIMEOUT, []) when no first byte arrived in the poll window (the
    serve loop reaps children between messages — PEP 475 auto-retries
    EINTR, so a SIGCHLD alone can never interrupt recvmsg)."""
    fds: list = []
    chunks = []
    first = True
    while True:
        try:
            data, ancdata, _flags, _addr = sock.recvmsg(1, 4096)
        except socket.timeout:
            if first:
                return _TIMEOUT, []
            continue  # mid-message: keep reading
        if not data:
            return None, []
        first = False
        for cmsg_level, cmsg_type, cmsg_data in ancdata:
            if cmsg_level == socket.SOL_SOCKET and cmsg_type == socket.SCM_RIGHTS:
                fda = array.array("i")
                fda.frombytes(cmsg_data[: len(cmsg_data) - len(cmsg_data) % fda.itemsize])
                fds.extend(fda)
        if data == b"\n":
            break
        chunks.append(data)
    return json.loads(b"".join(chunks).decode()), fds


def send_msg(sock: socket.socket, obj: dict, fds=()) -> None:
    payload = json.dumps(obj).encode() + b"\n"
    if fds:
        # fds ride on the FIRST byte; the rest streams plainly.
        sock.sendmsg(
            [payload[:1]],
            [(socket.SOL_SOCKET, socket.SCM_RIGHTS, array.array("i", fds).tobytes())],
        )
        sock.sendall(payload[1:])
    else:
        sock.sendall(payload)


def _reap(sock: socket.socket) -> None:
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return
        code = os.waitstatus_to_exitcode(status)
        try:
            send_msg(sock, {"exit": pid, "code": code})
        except OSError:
            return


def main() -> None:
    # Preload the worker stack BEFORE the serve loop: every forked worker
    # inherits these imports copy-on-write.
    from ray_tpu._private import worker_main  # noqa: F401  (heavy import)

    sock = socket.socket(fileno=int(sys.argv[1]))  # requests (recv only)
    resp = socket.socket(fileno=int(sys.argv[2]))  # responses (send only)
    # 1s poll between messages: child exits are reaped and reported within
    # a second even when no fork requests arrive.
    sock.settimeout(1.0)

    while True:
        try:
            req, fds = _recv_msg(sock)
        except OSError:
            break
        if req is _TIMEOUT:
            _reap(resp)
            continue
        if req is None:
            break
        _reap(resp)
        pid = os.fork()
        if pid == 0:
            code = 0
            try:
                if len(fds) >= 2:
                    os.dup2(fds[0], 1)
                    os.dup2(fds[1], 2)
                for fd in fds:
                    if fd > 2:
                        os.close(fd)
                sock.close()
                resp.close()
                for k, v in (req.get("env") or {}).items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = str(v)
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                from ray_tpu._private import worker_main as wm

                wm.main()
            except BaseException:  # noqa: BLE001 - the child must not
                import traceback   # return into the zygote's serve loop

                traceback.print_exc()
                code = 1
            finally:
                os._exit(code)
        for fd in fds:
            os.close(fd)
        try:
            send_msg(resp, {"forked": pid})
        except OSError:
            break
    # Parent exiting: children are re-parented to init; the raylet kills
    # them by pid through the normal worker teardown path.


if __name__ == "__main__":
    main()
