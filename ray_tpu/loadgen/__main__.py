"""CLI: ``python -m ray_tpu.loadgen --smoke [--json /tmp/serve_load.json]``.

Runs the self-contained Serve load harness (local cluster, HTTP off) and
prints/writes results in the perf-gate JSON shape. Exits nonzero if any
admitted request overran its deadline — that is the no-silent-overrun
invariant, enforced here the same way the chaos serve suite enforces it.
"""

from __future__ import annotations

import argparse
import sys

from ray_tpu.loadgen import run_smoke


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m ray_tpu.loadgen")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="short calibrate + 5x-overload run sized for CI",
    )
    parser.add_argument("--json", default=None, help="write results JSON here")
    parser.add_argument("--concurrency", type=int, default=16)
    parser.add_argument("--duration-s", type=float, default=2.0)
    parser.add_argument("--open-duration-s", type=float, default=2.0)
    parser.add_argument(
        "--overload-factor",
        type=float,
        default=5.0,
        help="open-loop rate as a multiple of the calibrated closed-loop rate",
    )
    parser.add_argument("--timeout-s", type=float, default=1.0)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--max-batch-size", type=int, default=4)
    args = parser.parse_args(argv)

    if args.smoke:
        args.duration_s = min(args.duration_s, 2.0)
        args.open_duration_s = min(args.open_duration_s, 2.0)

    out = run_smoke(
        args.json,
        closed_concurrency=args.concurrency,
        closed_duration_s=args.duration_s,
        open_duration_s=args.open_duration_s,
        overload_factor=args.overload_factor,
        timeout_s=args.timeout_s,
        num_replicas=args.replicas,
        max_batch_size=args.max_batch_size,
    )
    if out["serve_overruns"] > 0:
        print(
            f"FAIL: {out['serve_overruns']} admitted request(s) overran "
            "their deadline",
            file=sys.stderr,
        )
        return 1
    if out["serve_errors"] > 0:
        print(
            f"FAIL: {out['serve_errors']} request(s) failed with untyped "
            "errors: "
            + "; ".join(out["phases"]["open"].get("error_samples", []) or []),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
