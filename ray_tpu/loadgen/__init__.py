"""ray_tpu.loadgen: closed- and open-loop load harness for Serve.

Drives the in-process Router directly (no HTTP hop), so it measures the
serving stack — admission control, batching, routing, deadline enforcement —
rather than an HTTP client's connection pool. Two generators:

- **closed loop**: N concurrent issuers, each sending its next request only
  after the previous one completes. Measures sustainable throughput and the
  latency distribution at that throughput (classic closed-loop bias: it
  cannot overload the system, so it calibrates capacity).
- **open loop**: requests arrive on a fixed schedule regardless of
  completions (Poisson-free constant rate; see "Open Versus Closed" NSDI'06
  for why this is the one that exposes overload behavior). Run at a multiple
  of the closed-loop rate to verify the overload story: excess load must
  come back as *typed sheds* (DeploymentOverloadedError) or deadline cuts —
  never as admitted requests silently overrunning their deadline.

Results serialize to the same flat JSON shape as ray_perf, so
benchmarks/perf_gate.py gates serve_rps / serve_p99_ms alongside the core
runtime metrics.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc, telemetry
from ray_tpu._private.common import config
from ray_tpu.serve._private.common import DeploymentOverloadedError

__all__ = [
    "PhaseResult",
    "closed_loop",
    "open_loop",
    "percentile",
    "run_smoke",
    "to_gate_json",
]


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (no numpy dep)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class PhaseResult:
    """Outcome counters + latency samples for one load phase.

    Every issued request lands in exactly one bucket:

    - ``ok``            completed within its deadline (goodput)
    - ``shed_queue_full`` / ``shed_deadline``  typed admission sheds
    - ``deadline_cut``  admitted, then cut at the wire deadline (typed
                        DeadlineExceeded / TimeoutError — enforced, not lost)
    - ``overruns``      admitted and returned SUCCESS after the deadline —
                        the invariant violation the harness exists to catch
    - ``errors``        anything else
    """

    def __init__(self, name: str):
        self.name = name
        self.issued = 0
        self.ok = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.deadline_cut = 0
        self.overruns = 0
        self.errors = 0
        self.error_samples: List[str] = []
        self.latencies_ms: List[float] = []
        self.duration_s = 0.0

    @property
    def shed(self) -> int:
        return self.shed_queue_full + self.shed_deadline

    def summary(self) -> Dict[str, Any]:
        lat = sorted(self.latencies_ms)
        dur = max(self.duration_s, 1e-9)
        return {
            "issued": self.issued,
            "ok": self.ok,
            "rps": self.ok / dur,
            "offered_rps": self.issued / dur,
            "goodput_rps": self.ok / dur,
            "p50_ms": percentile(lat, 0.50),
            "p99_ms": percentile(lat, 0.99),
            "p999_ms": percentile(lat, 0.999),
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "deadline_cut": self.deadline_cut,
            "overruns": self.overruns,
            "errors": self.errors,
            "error_samples": list(self.error_samples),
            "duration_s": self.duration_s,
        }


async def _issue_one(
    router,
    deployment_id_str: str,
    payload: Any,
    timeout_s: float,
    res: PhaseResult,
) -> None:
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    deadline = t0 + timeout_s
    res.issued += 1
    try:
        await router.assign_request(
            deployment_id_str,
            {"call_method": "__call__", "request_id": "", "multiplexed_model_id": ""},
            (payload,),
            {},
            timeout_s=timeout_s,
        )
        now = loop.time()
        if now > deadline + config.rpc_deadline_grace_s:
            # Success delivered past deadline + grace: the enforcement chain
            # (router wait_for, replica-side TTL) failed to cut it.
            res.overruns += 1
        else:
            res.ok += 1
            res.latencies_ms.append((now - t0) * 1000.0)
    except DeploymentOverloadedError as e:
        if e.reason == "queue_full":
            res.shed_queue_full += 1
        else:
            res.shed_deadline += 1
    except (rpc.DeadlineExceeded, TimeoutError, asyncio.TimeoutError):
        res.deadline_cut += 1
    except Exception as e:  # noqa: BLE001 - loadgen must survive any failure
        res.errors += 1
        if len(res.error_samples) < 5:
            res.error_samples.append(f"{type(e).__name__}: {e}")


async def closed_loop(
    router,
    deployment_id_str: str,
    *,
    concurrency: int,
    duration_s: float,
    timeout_s: float,
    payload: Any = 0,
) -> PhaseResult:
    """N issuers, each one-request-at-a-time, for duration_s."""
    loop = asyncio.get_running_loop()
    res = PhaseResult("closed")
    start = loop.time()
    end = start + duration_s

    async def issuer() -> None:
        while loop.time() < end:
            await _issue_one(router, deployment_id_str, payload, timeout_s, res)

    await asyncio.gather(*(issuer() for _ in range(concurrency)))
    res.duration_s = loop.time() - start
    return res


async def open_loop(
    router,
    deployment_id_str: str,
    *,
    rps: float,
    duration_s: float,
    timeout_s: float,
    payload: Any = 0,
) -> PhaseResult:
    """Constant-rate arrivals for duration_s, independent of completions.

    Arrivals are batched per scheduler tick (all requests whose arrival time
    has passed fire together), so the generator sustains tens of thousands
    of rps without a per-request sleep.
    """
    loop = asyncio.get_running_loop()
    res = PhaseResult("open")
    spacing = 1.0 / max(rps, 1e-9)
    start = loop.time()
    end = start + duration_s
    tasks: List[asyncio.Task] = []
    fired = 0
    while True:
        now = loop.time()
        if now >= end:
            break
        due = int((now - start) / spacing) + 1
        while fired < due:
            tasks.append(
                rpc.spawn(
                    _issue_one(router, deployment_id_str, payload, timeout_s, res)
                )
            )
            fired += 1
        await asyncio.sleep(max(spacing, 0.0005))
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    res.duration_s = loop.time() - start
    return res


def to_gate_json(closed: PhaseResult, open_: PhaseResult) -> Dict[str, Any]:
    """Flatten both phases into the perf-gate results shape. Closed-loop
    supplies throughput + latency percentiles (measured un-overloaded);
    open-loop supplies goodput + shed counts under overload."""
    c, o = closed.summary(), open_.summary()
    return {
        "serve_rps": c["rps"],
        "serve_p50_ms": c["p50_ms"],
        "serve_p99_ms": c["p99_ms"],
        "serve_p999_ms": c["p999_ms"],
        "serve_goodput_rps": o["goodput_rps"],
        "serve_offered_rps": o["offered_rps"],
        "serve_shed": open_.shed,
        "serve_deadline_cut": o["deadline_cut"],
        "serve_overruns": c["overruns"] + o["overruns"],
        "serve_errors": c["errors"] + o["errors"],
        "phases": {"closed": c, "open": o},
    }


def run_smoke(
    json_path: Optional[str] = None,
    *,
    closed_concurrency: int = 16,
    closed_duration_s: float = 2.0,
    open_duration_s: float = 2.0,
    overload_factor: float = 5.0,
    timeout_s: float = 1.0,
    num_replicas: int = 2,
    max_batch_size: int = 4,
    verbose: bool = True,
) -> Dict[str, Any]:
    """Self-contained smoke run: start a local cluster + Serve (HTTP off),
    deploy a batched echo, run closed-loop to calibrate, then open-loop at
    overload_factor x the calibrated rate. Returns the gate JSON dict."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.serve import handle as handle_mod

    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.start(http_options={"enabled": False})

    @serve.deployment(
        num_replicas=num_replicas,
        max_ongoing_requests=8,
        max_queued_requests=64,
        max_batch_size=max_batch_size,
        batch_wait_timeout_s=0.002,
    )
    class Echo:
        async def __call__(self, batch):
            # Batched calling convention: list in, same-length list out.
            await asyncio.sleep(0.001)
            return batch

    serve.run(Echo.bind(), route_prefix=None)
    dep = "default#Echo"

    async def _phases():
        router = await handle_mod._get_router()
        closed = await closed_loop(
            router,
            dep,
            concurrency=closed_concurrency,
            duration_s=closed_duration_s,
            timeout_s=timeout_s,
        )
        calibrated = closed.ok / max(closed.duration_s, 1e-9)
        opened = await open_loop(
            router,
            dep,
            rps=max(200.0, calibrated * overload_factor),
            duration_s=open_duration_s,
            timeout_s=timeout_s,
        )
        return closed, opened, router.stats().get(dep, {})

    w = worker_mod.global_worker
    try:
        closed, opened, router_stats = w.run_async(
            _phases(), timeout=closed_duration_s + open_duration_s + 60
        )
        # Runtime-telemetry snapshot (non-destructive) while the cluster is
        # still up: the serve/rpc/object counters the run just exercised.
        tel_snapshot = telemetry.peek("loadgen", "loadgen")
    finally:
        try:
            serve.shutdown()
        finally:
            if owns_cluster:
                ray_tpu.shutdown()

    out = to_gate_json(closed, opened)
    out["router"] = router_stats
    out["telemetry"] = tel_snapshot
    if json_path:
        with open(json_path, "w") as f:
            json.dump(out, f, indent=2)
    if verbose:
        c, o = out["phases"]["closed"], out["phases"]["open"]
        print(
            f"closed : {c['rps']:8.1f} rps  "
            f"p50 {c['p50_ms']:6.1f}ms  p99 {c['p99_ms']:6.1f}ms  "
            f"p999 {c['p999_ms']:6.1f}ms  ({c['issued']} issued)"
        )
        print(
            f"open   : {o['offered_rps']:8.1f} offered rps -> "
            f"{o['goodput_rps']:8.1f} goodput rps  "
            f"shed {out['serve_shed']}  cut {o['deadline_cut']}  "
            f"overruns {out['serve_overruns']}  errors {out['serve_errors']}"
        )
    return out
