"""Unified static-analysis gate: ``python -m ray_tpu.devtools.lint``.

Runs every static pass over the package and exits non-zero on any finding:
the asyncio hazard linter (aio_lint), the RPC wire cross-checker
(rpc_check), the whole-program blocking-graph pass (rpc_flow: distributed
wait cycles, deadline propagation, task supervision), the
exception-propagation pass (exc_flow: wire error declarations, swallowed
control errors, retry-unsafe mutations, ack-before-persist), the
paired-resource lifecycle pass (lifecycle), the protocol FSM checker
(protocols), the
telemetry-registry pass (telemetry_lint, no ad-hoc stats dicts in runtime
code), and the stale-suppression audit (a ``disable=``/``allow-`` comment
that no longer masks any finding is itself a finding — dead waivers rot
into false confidence). This is the CI lint job's entry point; ``make
lint`` wraps it.

The gate also times itself: each pass's wall time is printed, and the
total is capped (``--budget-s``, or ``RAY_TPU_LINT_BUDGET_S``; default
120 s). A pass that grows superlinearly fails the gate before it quietly
turns the pre-merge loop into a coffee break.
"""

from __future__ import annotations

import argparse
import io
import os
import sys
import time
import tokenize
from typing import Callable, Dict, List, Optional, Set, Tuple

from ray_tpu.devtools import (
    aio_lint,
    exc_flow,
    lifecycle,
    protocols,
    rpc_check,
    rpc_flow,
    telemetry_lint,
)

_PASSES = (
    "aio-lint + rpc-check + rpc-flow + exc-flow + lifecycle + protocols"
    " + telemetry-lint + suppression-audit"
)

RULE_STALE = "stale-suppression"
RULE_BUDGET = "lint-over-budget"

_DEFAULT_BUDGET_S = 120.0


def audit_suppressions(paths: List[str]) -> List[aio_lint.Finding]:
    """Flag suppression comments that no longer mask any raw finding.

    Re-runs every pass with ``apply_suppressions=False`` and checks each
    ``# aio-lint: disable=`` / ``# rpc-flow: disable=`` /
    ``# lifecycle: disable=`` / ``# protocol: disable=`` /
    ``# telemetry: allow-adhoc-stats`` comment against the raw findings of
    its own family on the line it covers (the comment's line and the line
    below, mirroring the passes' scoping). The ``aio-lint`` syntax is
    shared by rpc_check, so its comments are validated against both
    passes' findings.
    """
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(aio_lint.iter_py_files(path))
        else:
            files.append(path)

    raw = {
        "aio-lint": (
            aio_lint.lint_paths(paths, apply_suppressions=False)
            + rpc_check.check(paths, apply_suppressions=False)
        ),
        "rpc-flow": rpc_flow.check(paths, apply_suppressions=False),
        "exc-flow": exc_flow.check(paths, apply_suppressions=False),
        "lifecycle": lifecycle.lint_paths(paths, apply_suppressions=False),
        "protocol": protocols.check(paths, apply_suppressions=False),
        "telemetry": telemetry_lint.lint_paths(paths, apply_suppressions=False),
    }
    # family -> abspath -> line -> rules found there without suppression
    idx: Dict[str, Dict[str, Dict[int, Set[str]]]] = {}
    for family, findings in raw.items():
        fam = idx.setdefault(family, {})
        for f in findings:
            fam.setdefault(os.path.abspath(f.path), {}).setdefault(
                f.line, set()
            ).add(f.rule)

    regexes = {
        "aio-lint": aio_lint._SUPPRESS_RE,
        "rpc-flow": rpc_flow._SUPPRESS_RE,
        "exc-flow": exc_flow._SUPPRESS_RE,
        "lifecycle": lifecycle._SUPPRESS_RE,
        "protocol": protocols._SUPPRESS_RE,
        "telemetry": telemetry_lint._ALLOW_RE,
    }
    out: List[aio_lint.Finding] = []
    for fpath in files:
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        apath = os.path.abspath(fpath)
        # Only genuine comment tokens: the suppression syntax also appears
        # in docstrings and message strings (this file included), which are
        # not waivers.
        comments: List = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            continue
        for lineno, text in comments:
            for family, rex in regexes.items():
                m = rex.search(text)
                if not m:
                    continue
                rules: Optional[Set[str]] = None
                if m.groups():
                    rules = {
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    }
                by_line = idx.get(family, {}).get(apath, {})
                used = False
                for covered in (lineno, lineno + 1):
                    found = by_line.get(covered)
                    if not found:
                        continue
                    if rules is None or "all" in rules or (found & rules):
                        used = True
                        break
                if not used:
                    out.append(
                        aio_lint.Finding(
                            fpath,
                            lineno,
                            0,
                            RULE_STALE,
                            f"{family} suppression masks no finding any "
                            "more — the code it waived was fixed or moved; "
                            "delete the comment",
                        )
                    )
    return out


def run_timed(
    paths: List[str],
) -> Tuple[List[aio_lint.Finding], List[Tuple[str, float]]]:
    """All passes + audit, with per-pass wall times."""
    stages: List[Tuple[str, Callable[[], List[aio_lint.Finding]]]] = [
        ("aio-lint", lambda: list(aio_lint.lint_paths(paths))),
        ("rpc-check", lambda: rpc_check.check(paths)),
        ("rpc-flow", lambda: rpc_flow.check(paths)),
        ("exc-flow", lambda: exc_flow.check(paths)),
        ("lifecycle", lambda: lifecycle.lint_paths(paths)),
        ("protocols", lambda: protocols.check(paths)),
        ("telemetry-lint", lambda: telemetry_lint.lint_paths(paths)),
        ("suppression-audit", lambda: audit_suppressions(paths)),
    ]
    findings: List[aio_lint.Finding] = []
    timings: List[Tuple[str, float]] = []
    for name, fn in stages:
        t0 = time.monotonic()
        findings.extend(fn())
        timings.append((name, time.monotonic() - t0))
    return findings, timings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="run all ray_tpu static-analysis passes",
    )
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument(
        "--budget-s",
        type=float,
        default=float(
            os.environ.get("RAY_TPU_LINT_BUDGET_S", _DEFAULT_BUDGET_S)
        ),
        help="fail if the whole gate takes longer than this many seconds "
        "(env RAY_TPU_LINT_BUDGET_S; <= 0 disables)",
    )
    args = parser.parse_args(argv)
    paths = args.paths or [aio_lint._default_root()]

    findings, timings = run_timed(paths)
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    for f in findings:
        print(f)
    total = sum(dt for _, dt in timings)
    slowest = ", ".join(
        f"{name} {dt:.2f}s"
        for name, dt in sorted(timings, key=lambda t: -t[1])[:3]
    )
    print(f"lint: {total:.2f}s wall ({slowest})")
    over_budget = 0.0 < args.budget_s < total
    if over_budget:
        print(
            f"lint: {RULE_BUDGET}: gate took {total:.2f}s, budget is "
            f"{args.budget_s:g}s — profile the slowest pass above or raise "
            "RAY_TPU_LINT_BUDGET_S deliberately"
        )
    if findings or over_budget:
        if findings:
            print(f"lint: {len(findings)} finding(s) across {_PASSES}")
        return 1
    print(f"lint: clean ({_PASSES})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
