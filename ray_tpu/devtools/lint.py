"""Unified static-analysis gate: ``python -m ray_tpu.devtools.lint``.

Runs every static pass over the package and exits non-zero on any finding:
the asyncio hazard linter (aio_lint), the RPC wire cross-checker
(rpc_check), the paired-resource lifecycle pass (lifecycle), the protocol
FSM checker (protocols), and the telemetry-registry pass (telemetry_lint,
no ad-hoc stats dicts in runtime code). This is the CI lint job's entry
point; ``make lint`` wraps it.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ray_tpu.devtools import (
    aio_lint,
    lifecycle,
    protocols,
    rpc_check,
    telemetry_lint,
)

_PASSES = "aio-lint + rpc-check + lifecycle + protocols + telemetry-lint"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="run all ray_tpu static-analysis passes",
    )
    parser.add_argument("paths", nargs="*", default=None)
    args = parser.parse_args(argv)
    paths = args.paths or [aio_lint._default_root()]

    findings = list(aio_lint.lint_paths(paths))
    findings.extend(rpc_check.check(paths))
    findings.extend(lifecycle.lint_paths(paths))
    findings.extend(protocols.check(paths))
    findings.extend(telemetry_lint.lint_paths(paths))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s) across {_PASSES}")
        return 1
    print(f"lint: clean ({_PASSES})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
