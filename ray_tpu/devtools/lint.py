"""Unified static-analysis gate: ``python -m ray_tpu.devtools.lint``.

Runs the asyncio hazard linter (aio_lint) and the RPC wire cross-checker
(rpc_check) over the package and exits non-zero on any finding. This is the
CI lint job's entry point; ``make lint`` wraps it.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from ray_tpu.devtools import aio_lint, rpc_check


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="run all ray_tpu static-analysis passes",
    )
    parser.add_argument("paths", nargs="*", default=None)
    args = parser.parse_args(argv)
    paths = args.paths or [aio_lint._default_root()]

    findings = list(aio_lint.lint_paths(paths))
    findings.extend(rpc_check.check(paths))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    for f in findings:
        print(f)
    if findings:
        print(f"lint: {len(findings)} finding(s) across aio-lint + rpc-check")
        return 1
    print("lint: clean (aio-lint + rpc-check)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
