"""telemetry-unregistered-stat: no new ad-hoc stats dicts in runtime code.

PR 7 introduced ``ray_tpu._private.telemetry`` as the single registry for
runtime counters/gauges/histograms: cells registered there are flushed to
the GCS aggregate, exported on the dashboard's ``/metrics``, and visible to
the chaos flight recorder. A bare ``self.stats = {...}`` dict in runtime
code is invisible to all of that — it works in the one code path that
reads it and silently disappears from cluster-wide observability.

This pass flags dict-literal assignments to ``*stats``-named targets inside
``_private`` packages (``ray_tpu/_private/``, ``ray_tpu/serve/_private/``)
and the instrumented data layer (``ray_tpu/data/`` — runtime code since the
ingest pipeline gained telemetry families).
Legacy dicts that intentionally stay (they back an existing ``stats()``
surface consumed by loadgen/chaos) carry an explicit waiver:

    self.stats = {...}  # telemetry: allow-adhoc-stats

on the flagged line or the line directly above it. New code should register
a telemetry family instead (``telemetry.counter/gauge/histogram``).

Run: ``python -m ray_tpu.devtools.telemetry_lint [paths]``.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Set

from ray_tpu.devtools.aio_lint import Finding, iter_py_files

RULE = "telemetry-unregistered-stat"

_ALLOW_RE = re.compile(r"#\s*telemetry:\s*allow-adhoc-stats")
_STATS_NAME_RE = re.compile(r"(^|_)stats$")


def _allowed_lines(source: str) -> Set[int]:
    out: Set[int] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        if _ALLOW_RE.search(text):
            out.add(i)
    return out


def _target_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _in_private_pkg(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    if "_private" in parts:
        return True
    # The data layer is runtime code with registered telemetry families;
    # hold it to the same no-ad-hoc-stats bar.
    for i, p in enumerate(parts[:-1]):
        if p == "ray_tpu" and parts[i + 1] == "data":
            return True
    return False


def lint_file(path: str, apply_suppressions: bool = True) -> List[Finding]:
    if not _in_private_pkg(path):
        return []
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # other passes report parse failures
    allowed = _allowed_lines(source) if apply_suppressions else set()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, ast.Dict):
            continue
        for tgt in targets:
            name = _target_name(tgt)
            if name is None or not _STATS_NAME_RE.search(name):
                continue
            if node.lineno in allowed or (node.lineno - 1) in allowed:
                continue
            findings.append(
                Finding(
                    path=path,
                    line=node.lineno,
                    col=node.col_offset,
                    rule=RULE,
                    message=(
                        f"ad-hoc stats dict {name!r} in runtime code: "
                        "register a ray_tpu._private.telemetry family "
                        "(counter/gauge/histogram) so it reaches /metrics "
                        "and the flight recorder, or waive with "
                        "'# telemetry: allow-adhoc-stats'"
                    ),
                )
            )
    return findings


def lint_paths(
    paths: Iterable[str], apply_suppressions: bool = True
) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for f in iter_py_files(path):
                findings.extend(lint_file(f, apply_suppressions=apply_suppressions))
        else:
            findings.extend(lint_file(path, apply_suppressions=apply_suppressions))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.telemetry_lint",
        description="flag ad-hoc stats dicts outside the telemetry registry",
    )
    parser.add_argument("paths", nargs="*", default=None)
    args = parser.parse_args(argv)
    if not args.paths:
        from ray_tpu.devtools.aio_lint import _default_root

        args.paths = [_default_root()]
    findings = lint_paths(args.paths)
    for f in findings:
        print(f)
    if findings:
        print(f"telemetry-lint: {len(findings)} finding(s)")
        return 1
    print("telemetry-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
