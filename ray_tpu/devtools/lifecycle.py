"""Paired-resource lifecycle dataflow pass.

Every ledger bug PR 2's chaos subsystem caught — double-grant, quota
stranding, pin leaks — was an unbalanced acquire/release on one of a small
set of paired-resource APIs. This pass encodes those pairs in a registry
and runs an intraprocedural abstract interpretation over each function's
AST, tracking a per-resource state lattice:

    U (unheld) --acquire--> H (held) --release--> R (released)
    join(a, b) = a if a == b else M (maybe)

and flags the paths where the release can be skipped:

Rules
-----
- ``lifecycle-leak-exception``: while a resource is held and its release is
  not in an enclosing ``finally``, a call that may raise is made — an
  exception propagates past the release.
- ``lifecycle-leak-return``: a ``return`` (or falling off the end of the
  function) while a scoped resource is held and unprotected.
- ``lifecycle-held-await``: an ``await`` is crossed while holding an
  unprotected resource. Awaits are cancellation points: ``Task.cancel``
  raises ``CancelledError`` out of the await and skips every statement
  after it that is not in a ``finally`` — exactly the
  ``BandwidthQuota.acquire`` leak class.
- ``lifecycle-double-release``: a release when the state is already R
  (released on this path).

Pairs come in two modes. **Scoped** pairs (pull-quota, lease-pool) must
release within the acquiring function — holding one across a function
boundary is itself a bug, so every rule applies unconditionally. **Ledger**
pairs (store pins, object-store holds, granted-lease bookkeeping, the
raylet resource ledger) legitimately outlive the acquiring function; for
those, the leak/await rules only fire in functions that contain *both* an
acquire and a release for the same resource — i.e. functions that clearly
intend a balanced scope.

The pass is a tripwire, not a soundness proof: resources are keyed by the
receiver's dotted expression (``self.pull_manager``), aliasing is not
tracked, and interprocedural flows are out of scope (that is what the
chaos suite is for).

Suppression: ``# lifecycle: disable=<rule>[,<rule>]`` (or ``disable=all``)
on the flagged line or the line directly above it.

Run: ``python -m ray_tpu.devtools.lifecycle [paths]``.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.aio_lint import (
    Finding,
    _default_root,
    _dotted,
    iter_py_files,
)

RULE_LEAK_EXC = "lifecycle-leak-exception"
RULE_LEAK_RETURN = "lifecycle-leak-return"
RULE_HELD_AWAIT = "lifecycle-held-await"
RULE_DOUBLE_RELEASE = "lifecycle-double-release"

ALL_RULES = (RULE_LEAK_EXC, RULE_LEAK_RETURN, RULE_HELD_AWAIT, RULE_DOUBLE_RELEASE)

_SUPPRESS_RE = re.compile(r"#\s*lifecycle:\s*disable=([\w\-, ]+)")

# Abstract states. U/H/R as above; M = maybe-held (branch join disagreed),
# on which no rule fires — a conditional release is assumed deliberate.
U, H, R, M = "U", "H", "R", "M"

_DEAD = "__dead__"  # path terminated (return/raise) — excluded from joins


@dataclass(frozen=True)
class PairSpec:
    """One acquire/release pair.

    ``receivers`` restricts matching to call receivers whose dotted chain
    ends in one of the given names (``self.pull_manager.acquire`` matches
    receiver ``pull_manager``); ``None`` matches any receiver, for
    project-unique method names like ``_record_granted``.
    """

    name: str
    acquire: Tuple[str, ...]
    release: Tuple[str, ...]
    receivers: Optional[Tuple[str, ...]]
    scoped: bool
    doc: str


REGISTRY: Tuple[PairSpec, ...] = (
    PairSpec(
        name="pull-quota",
        acquire=("acquire",),
        release=("release",),
        receivers=("pull_manager",),
        scoped=True,
        doc="BandwidthQuota bytes_in_flight/active admission "
        "(ray_tpu/_private/pull_manager.py)",
    ),
    PairSpec(
        name="lease-pool",
        acquire=("acquire",),
        release=("release",),
        receivers=("lease_pool",),
        scoped=True,
        doc="core_worker LeasePool worker lease "
        "(ray_tpu/_private/core_worker.py)",
    ),
    PairSpec(
        name="store-pin",
        acquire=("pin",),
        release=("unpin",),
        receivers=("store",),
        scoped=False,
        doc="Store object pin refcount (ray_tpu/_private/store_core.py)",
    ),
    PairSpec(
        name="obj-holds",
        acquire=("get", "pull"),
        release=("release", "release_many", "release_counts"),
        receivers=("plasma",),
        scoped=False,
        doc="object-store client hold counts "
        "(ray_tpu/_private/object_store.py)",
    ),
    PairSpec(
        name="trace-span",
        acquire=("set_context",),
        release=("reset_context",),
        receivers=("tracing",),
        scoped=True,
        doc="PR 13 trace-context token: set_context returns a contextvar "
        "reset token that must be reset in the same function, or the span "
        "leaks onto unrelated work sharing the thread/context "
        "(ray_tpu/util/tracing.py)",
    ),
    PairSpec(
        name="grant-ledger",
        acquire=("_record_granted",),
        release=("_mark_lease_released", "_burn_lease_id"),
        receivers=None,
        scoped=False,
        doc="raylet granted-lease dedup ledger (ray_tpu/_private/raylet.py)",
    ),
)

# The raylet resource ledger is not a method pair but an assignment idiom:
#   self.available = self.available - demand   (deduct / acquire)
#   self.available = self.available + demand   (refund / release)
# Tracked as a ledger-mode pseudo-pair keyed on the assigned attribute.
_LEDGER_ATTR = "available"
_LEDGER_PAIR = PairSpec(
    name="resource-ledger",
    acquire=(),
    release=(),
    receivers=None,
    scoped=False,
    doc="raylet available-resources deduct/refund (ray_tpu/_private/raylet.py)",
)

# Calls that cannot meaningfully raise between acquire and release — pure
# bookkeeping; flagging them would force try/finally around straight-line
# arithmetic.
_EXEMPT_BUILTINS = {
    "len",
    "int",
    "float",
    "str",
    "repr",
    "bool",
    "list",
    "dict",
    "tuple",
    "set",
    "frozenset",
    "min",
    "max",
    "sum",
    "abs",
    "sorted",
    "isinstance",
    "getattr",
    "hasattr",
    "id",
    "range",
    "enumerate",
    "zip",
}
_EXEMPT_PREFIXES = ("logger.", "logging.", "log.", "time.monotonic", "time.time")


def _suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _match_call(node: ast.Call) -> Optional[Tuple[PairSpec, str, str]]:
    """(pair, resource key, 'acquire'|'release') for a registry call site.

    Method *definitions* don't get here (they aren't Call nodes), so the
    implementations of acquire/release themselves are never self-flagged.
    """
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    method = func.attr
    recv = _dotted(func.value)
    recv_last = recv.rsplit(".", 1)[-1] if recv else None
    for pair in REGISTRY:
        if method not in pair.acquire and method not in pair.release:
            continue
        if pair.receivers is not None:
            if recv_last is None or recv_last not in pair.receivers:
                continue
        elif recv is None:
            continue
        kind = "acquire" if method in pair.acquire else "release"
        key = f"{pair.name}:{recv or '?'}"
        return pair, key, kind
    return None


def _match_ledger_assign(node: ast.Assign) -> Optional[Tuple[str, str]]:
    """(resource key, kind) for ``x.available = x.available ± expr``."""
    if len(node.targets) != 1:
        return None
    tgt = node.targets[0]
    if not (isinstance(tgt, ast.Attribute) and tgt.attr == _LEDGER_ATTR):
        return None
    val = node.value
    if not isinstance(val, ast.BinOp) or not isinstance(
        val.op, (ast.Add, ast.Sub)
    ):
        return None
    tgt_dotted = _dotted(tgt)
    left_dotted = _dotted(val.left)
    if tgt_dotted is None or tgt_dotted != left_dotted:
        return None
    kind = "acquire" if isinstance(val.op, ast.Sub) else "release"
    return f"{_LEDGER_PAIR.name}:{tgt_dotted}", kind


def _pair_for_key(key: str) -> PairSpec:
    name = key.split(":", 1)[0]
    if name == _LEDGER_PAIR.name:
        return _LEDGER_PAIR
    for pair in REGISTRY:
        if pair.name == name:
            return pair
    raise KeyError(key)


class _FnLifecycle:
    """Abstract interpretation of one function body."""

    def __init__(self, fn: ast.AST, path: str) -> None:
        self.fn = fn
        self.path = path
        self.findings: List[Finding] = []
        self._flagged: Set[Tuple[str, str, int]] = set()
        # Stack of key-sets whose release sits in an enclosing ``finally``.
        self.protected: List[Set[str]] = []
        self.released_keys = self._collect_releases(fn.body)

    # -- pre-pass -----------------------------------------------------------

    def _collect_releases(self, body: List[ast.stmt]) -> Set[str]:
        """Keys this function releases anywhere (gates ledger-mode rules)."""
        out: Set[str] = set()
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(node, ast.Call):
                    hit = _match_call(node)
                    if hit and hit[2] == "release":
                        out.add(hit[1])
                elif isinstance(node, ast.Assign):
                    led = _match_ledger_assign(node)
                    if led and led[1] == "release":
                        out.add(led[0])
        return out

    def _releases_in(self, stmts: List[ast.stmt]) -> Set[str]:
        return self._collect_releases(stmts)

    # -- reporting ----------------------------------------------------------

    def _relevant(self, key: str) -> bool:
        return _pair_for_key(key).scoped or key in self.released_keys

    def _is_protected(self, key: str) -> bool:
        return any(key in layer for layer in self.protected)

    def _emit(self, node: ast.AST, key: str, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        mark = (key, rule, line)
        if mark in self._flagged:
            return
        self._flagged.add(mark)
        self.findings.append(
            Finding(self.path, line, getattr(node, "col_offset", 0), rule, message)
        )

    def _flag_held(self, node: ast.AST, state: Dict[str, str], rule: str,
                   what: str) -> None:
        for key, st in state.items():
            if st != H or self._is_protected(key) or not self._relevant(key):
                continue
            pair = _pair_for_key(key)
            self._emit(
                node,
                key,
                rule,
                f"{what} while holding {key} ({pair.doc}) with no "
                f"enclosing finally to release it",
            )

    # -- lattice ------------------------------------------------------------

    @staticmethod
    def _join(a: Dict[str, str], b: Dict[str, str]) -> Dict[str, str]:
        if a.get(_DEAD):
            return dict(b)
        if b.get(_DEAD):
            return dict(a)
        out: Dict[str, str] = {}
        for key in set(a) | set(b):
            sa, sb = a.get(key, U), b.get(key, U)
            out[key] = sa if sa == sb else M
        return out

    # -- statements ---------------------------------------------------------

    def _block(self, stmts: List[ast.stmt], state: Dict[str, str]) -> None:
        for stmt in stmts:
            if state.get(_DEAD):
                return
            self._stmt(stmt, state)

    def _stmt(self, node: ast.stmt, state: Dict[str, str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs analyzed as their own functions
        if isinstance(node, ast.Try):
            self._try(node, state)
        elif isinstance(node, ast.If):
            self._expr(node.test, state)
            s_then = dict(state)
            s_else = dict(state)
            self._block(node.body, s_then)
            self._block(node.orelse, s_else)
            state.clear()
            state.update(self._join(s_then, s_else))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter, state)
            if isinstance(node, ast.AsyncFor):
                self._flag_held(node, state, RULE_HELD_AWAIT,
                                "async-for suspension point crossed")
            s_in = dict(state)
            self._block(node.body, state)
            state.update(self._join(s_in, state))
            self._block(node.orelse, state)
        elif isinstance(node, ast.While):
            self._expr(node.test, state)
            s_in = dict(state)
            self._block(node.body, state)
            state.update(self._join(s_in, state))
            self._block(node.orelse, state)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._expr(item.context_expr, state)
            if isinstance(node, ast.AsyncWith):
                self._flag_held(node, state, RULE_HELD_AWAIT,
                                "async-with suspension point crossed")
            self._block(node.body, state)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self._expr(node.value, state)
            self._flag_return(node, state)
            state[_DEAD] = True
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._expr(node.exc, state)
            self._flag_held(node, state, RULE_LEAK_EXC, "raise propagates")
            state[_DEAD] = True
        elif isinstance(node, ast.Assign):
            led = _match_ledger_assign(node)
            self._expr(node.value, state)
            for tgt in node.targets:
                self._expr(tgt, state)
            if led is not None:
                self._transition(node, led[0], led[1], state)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._expr(child, state)

    def _flag_return(self, node: ast.AST, state: Dict[str, str]) -> None:
        for key, st in state.items():
            if key == _DEAD or st != H:
                continue
            if self._is_protected(key) or not _pair_for_key(key).scoped:
                continue
            pair = _pair_for_key(key)
            self._emit(
                node,
                key,
                RULE_LEAK_RETURN,
                f"function can return while still holding {key} "
                f"({pair.doc}); release it on this path or move the "
                f"release to a finally",
            )

    def _try(self, node: ast.Try, state: Dict[str, str]) -> None:
        entry = dict(state)
        self.protected.append(self._releases_in(node.finalbody))
        s_body = dict(state)
        self._block(node.body, s_body)
        # A handler can run after any prefix of the body: join entry with
        # after-body so releases inside the body stay conditional there.
        handler_in = self._join(entry, s_body)
        branches: List[Dict[str, str]] = []
        for handler in node.handlers:
            sh = dict(handler_in)
            self._block(handler.body, sh)
            branches.append(sh)
        s_orelse = dict(s_body)
        self._block(node.orelse, s_orelse)
        branches.append(s_orelse)
        self.protected.pop()
        out = branches[0]
        for br in branches[1:]:
            out = self._join(out, br)
        self._block(node.finalbody, out)
        state.clear()
        state.update(out)

    # -- expressions --------------------------------------------------------

    def _transition(self, node: ast.AST, key: str, kind: str,
                    state: Dict[str, str]) -> None:
        if kind == "acquire":
            state[key] = H
            return
        st = state.get(key, U)
        if st == R:
            pair = _pair_for_key(key)
            self._emit(
                node,
                key,
                RULE_DOUBLE_RELEASE,
                f"{key} ({pair.doc}) already released on this path — "
                f"double release corrupts the ledger",
            )
        elif st == H:
            state[key] = R
        # U: release of something acquired elsewhere (ledger mode) — fine.
        # M: conditional release pattern — deliberately quiet.

    def _risky_call(self, node: ast.Call) -> bool:
        name = _dotted(node.func) or ""
        if name in _EXEMPT_BUILTINS:
            return False
        if any(name.startswith(p) or name == p.rstrip(".")
               for p in _EXEMPT_PREFIXES):
            return False
        return True

    def _expr(self, node: ast.AST, state: Dict[str, str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Await):
            held_before = {k for k, v in state.items() if v == H}
            self._expr(node.value, state)
            # Keys acquired *by* this await (``await pm.acquire()``) or
            # released by it are excluded: only pre-held keys are at risk
            # from this suspension point's cancellation window.
            at_risk = {
                k for k in held_before
                if state.get(k) == H and not self._is_protected(k)
                and self._relevant(k)
            }
            for key in at_risk:
                pair = _pair_for_key(key)
                self._emit(
                    node,
                    key,
                    RULE_HELD_AWAIT,
                    f"await crossed while holding {key} ({pair.doc}) "
                    f"outside a finally — cancellation at this suspension "
                    f"point skips the release",
                )
            return
        if isinstance(node, ast.Call):
            hit = _match_call(node)
            for arg in node.args:
                self._expr(arg, state)
            for kw in node.keywords:
                self._expr(kw.value, state)
            self._expr(node.func, state)
            if hit is not None:
                self._transition(node, hit[1], hit[2], state)
            elif self._risky_call(node):
                self._flag_held(
                    node, state, RULE_LEAK_EXC,
                    f"call to {_dotted(node.func) or 'dynamic target'}() "
                    f"may raise",
                )
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, state)

    # -- entry --------------------------------------------------------------

    def run(self) -> List[Finding]:
        state: Dict[str, str] = {}
        self._block(self.fn.body, state)
        if not state.get(_DEAD):
            self._flag_return(self.fn, state)
        return self.findings


def lint_source(
    source: str, path: str = "<string>", apply_suppressions: bool = True
) -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "parse-error", str(e.msg))]
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_FnLifecycle(node, path).run())
    sup = _suppressions(source) if apply_suppressions else {}

    def suppressed(f: Finding) -> bool:
        for line in (f.line, f.line - 1):
            rules = sup.get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return sorted(
        (f for f in findings if not suppressed(f)),
        key=lambda f: (f.line, f.col, f.rule),
    )


def lint_file(path: str, apply_suppressions: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path, apply_suppressions=apply_suppressions)


def lint_paths(
    paths: Iterable[str], apply_suppressions: bool = True
) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for f in iter_py_files(path):
                findings.extend(lint_file(f, apply_suppressions=apply_suppressions))
        else:
            findings.extend(lint_file(path, apply_suppressions=apply_suppressions))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lifecycle",
        description="paired-resource lifecycle linter "
        "(see module docstring for rules)",
    )
    parser.add_argument("paths", nargs="*", default=None)
    args = parser.parse_args(argv)
    paths = args.paths or [_default_root()]
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"lifecycle: {len(findings)} finding(s)")
        return 1
    print("lifecycle: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
