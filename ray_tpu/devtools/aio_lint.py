"""AST-based asyncio hazard linter for the single-loop control plane.

The GCS/raylet/core-worker tier is cooperative asyncio: every ``await`` is a
potential interleaving point and the only mutual exclusion is "don't await
between the read and the write". These rules encode the failure modes that
have actually bitten this codebase (see rpc.py's ``spawn()`` docstring for
the GC'd fire-and-forget task bug) in the spirit of compositional pre-commit
race detectors (RacerD) rather than whole-program model checking: each rule
is a local, per-function pattern with an explicit suppression escape hatch.

Rules
-----
- ``blocking-call``: a known blocking call (``time.sleep``, sync
  ``subprocess``/``socket``/``urllib``/``requests``/``shutil`` entry
  points, builtin ``open``, and the pathlib convenience I/O methods
  ``read_text``/``read_bytes``/``write_text``/``write_bytes`` on any
  receiver) lexically inside an ``async def``. Nested *sync* ``def``s are
  exempt — they are usually ``run_in_executor`` targets.
- ``raw-create-task``: ``asyncio.create_task`` / ``loop.create_task`` /
  ``asyncio.ensure_future`` anywhere. The event loop holds only weak task
  references; every background task must go through ``rpc.spawn()`` (or an
  owner that parks a strong reference and is suppressed explicitly).
- ``unawaited-coro``: a bare expression statement calling a *locally
  defined* ``async def`` (module function or method of the enclosing class)
  without ``await`` — the coroutine object is created and dropped.
- ``await-interleave``: asyncio TOCTOU. The function reads a shared
  container (an attribute initialised to a dict/list/set/deque in the
  class's ``__init__``, or a module-global container), then crosses an
  interleave point, then mutates that container without re-reading it
  after the suspension and without holding an ``asyncio.Lock``. Interleave
  points are explicit ``await``s, the implicit awaits of ``async for`` /
  ``async with`` (including the back-edge ``__anext__``), async-generator
  ``yield``s (the consumer runs before the next line), and async
  comprehensions (``[... async for ...]`` awaits in expression position).
  Purely additive mutations (``append``/``add``/``extend``) are not
  treated as hazardous writes — the lost-update shape needs a
  read-modify-write or a rebind/del.

Suppression: ``# aio-lint: disable=<rule>[,<rule>]`` (or ``disable=all``)
on the flagged line or the line directly above it.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

RULE_BLOCKING = "blocking-call"
RULE_CREATE_TASK = "raw-create-task"
RULE_UNAWAITED = "unawaited-coro"
RULE_INTERLEAVE = "await-interleave"

ALL_RULES = (RULE_BLOCKING, RULE_CREATE_TASK, RULE_UNAWAITED, RULE_INTERLEAVE)

# Dotted call targets that block the event loop. Matched against the
# longest resolvable attribute chain (``a.b.c(...)`` -> "a.b.c"), so an
# aliased module import (``import subprocess as sp``) is not caught — the
# linter is a tripwire, not a soundness proof.
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "os.system",
    "os.popen",
    "os.waitpid",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.head",
    "requests.request",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copyfile",
    "shutil.copytree",
    "shutil.copymode",
    "shutil.copystat",
    "shutil.move",
    "shutil.rmtree",
}

# Builtin calls that do synchronous file I/O.
_BLOCKING_BUILTINS = {"open"}

# Method names that do synchronous file I/O on any receiver: the pathlib
# convenience readers/writers (``cfg_path.read_text()``). Matched on the
# trailing attribute alone because the receiver is an arbitrary Path
# expression, not an importable module chain.
_BLOCKING_METHODS = {
    "read_text",
    "read_bytes",
    "write_text",
    "write_bytes",
}

# Container constructors that mark an attribute as shared mutable state.
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict"}

# Mutating container methods that can lose a concurrent update (read-modify-
# write or removal). Additive ops (append/add/extend/appendleft) are
# deliberately excluded: interleaved appends merge, they don't clobber.
_MUTATING_METHODS = {
    "pop",
    "popitem",
    "clear",
    "update",
    "remove",
    "discard",
    "setdefault",
    "insert",
}

_SUPPRESS_RE = re.compile(r"#\s*aio-lint:\s*disable=([\w\-, ]+)")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


def _suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> set of suppressed rule names ('all' wildcard)."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """Resolve ``a.b.c`` attribute chains to a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        # e.g. asyncio.get_running_loop().create_task -> "().create_task";
        # we only care about the trailing attribute in that case.
        return "()." + ".".join(reversed(parts)) if parts else None
    return None


def _ctor_name(value: ast.AST) -> Optional[str]:
    """Name of the constructor if ``value`` builds a fresh container."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if name in _CONTAINER_CTORS:
            return name
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    """Heuristic: does this ``async with`` context expression look like a
    mutual-exclusion primitive (``self._lock``, ``sem``, ``self.mu``...)?"""
    name = None
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    if name is None:
        return False
    lowered = name.lower()
    return any(tok in lowered for tok in ("lock", "mutex", "sem", "guard"))


class _ModuleIndex:
    """Per-module symbol tables the per-function passes consult."""

    def __init__(self, tree: ast.Module):
        self.module_async: Set[str] = set()
        self.class_async: Dict[str, Set[str]] = {}
        self.class_shared: Dict[str, Set[str]] = {}
        self.module_shared: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.AsyncFunctionDef):
                self.module_async.add(node.name)
            elif isinstance(node, ast.Assign) and _ctor_name(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_shared.add(tgt.id)
            elif isinstance(node, ast.ClassDef):
                methods: Set[str] = set()
                shared: Set[str] = set()
                for item in node.body:
                    if isinstance(item, ast.AsyncFunctionDef):
                        methods.add(item.name)
                    elif (
                        isinstance(item, ast.FunctionDef)
                        and item.name == "__init__"
                    ):
                        for stmt in ast.walk(item):
                            if not isinstance(stmt, ast.Assign):
                                continue
                            if not _ctor_name(stmt.value):
                                continue
                            for tgt in stmt.targets:
                                if (
                                    isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"
                                ):
                                    shared.add(tgt.attr)
                self.class_async[node.name] = methods
                self.class_shared[node.name] = shared


# Events for the interleaving state machine.
_EV_READ, _EV_WRITE, _EV_AWAIT = "read", "write", "await"


class _AsyncFnLinter:
    """Runs all per-function rules over one ``async def`` body in statement
    order, without descending into nested function definitions."""

    def __init__(
        self,
        fn: ast.AsyncFunctionDef,
        index: _ModuleIndex,
        class_name: Optional[str],
        path: str,
    ):
        self.fn = fn
        self.index = index
        self.class_name = class_name
        self.path = path
        self.findings: List[Finding] = []
        self.shared = (
            index.class_shared.get(class_name, set()) if class_name else set()
        )
        self.lock_depth = 0
        # attr -> state for the interleave machine:
        #   "read"           read seen, no await yet
        #   "read+await"     read, then crossed an await, not re-read since
        #   "revalidated"    re-read after the await (fresh view)
        self._state: Dict[str, str] = {}
        self._flagged: Set[str] = set()

    # -- shared-container classification ------------------------------------

    def _shared_attr(self, node: ast.AST) -> Optional[str]:
        """Return a stable key if ``node`` names a shared container."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.shared
        ):
            return "self." + node.attr
        if isinstance(node, ast.Name) and node.id in self.index.module_shared:
            return node.id
        return None

    def _record(self, ev: str, attr: str, node: ast.AST) -> None:
        if ev == _EV_READ:
            if self._state.get(attr) == "read+await":
                self._state[attr] = "revalidated"
            elif attr not in self._state:
                self._state[attr] = "read"
        elif ev == _EV_WRITE:
            if (
                self._state.get(attr) == "read+await"
                and self.lock_depth == 0
                and attr not in self._flagged
            ):
                self._flagged.add(attr)
                self._emit(
                    node,
                    RULE_INTERLEAVE,
                    f"{attr} is read, then an await interleaves, then it is "
                    "mutated without re-validation or an asyncio.Lock "
                    "(lost-update hazard: another task may have changed it "
                    "across the await)",
                )
            # A write ends the read-await-write window: statements are atomic
            # between awaits, so a completed mutation (including an atomic
            # ``+=`` read-modify-write) leaves nothing stale to write back.
            self._state.pop(attr, None)

    def _cross_await(self) -> None:
        for attr, st in self._state.items():
            if st in ("read", "revalidated"):
                self._state[attr] = "read+await"

    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(
                self.path,
                getattr(node, "lineno", self.fn.lineno),
                getattr(node, "col_offset", 0),
                rule,
                msg,
            )
        )

    # -- walk ---------------------------------------------------------------

    def run(self) -> List[Finding]:
        for stmt in self.fn.body:
            self._visit(stmt)
        return self.findings

    def _visit(self, node: ast.AST) -> None:  # noqa: C901 - dispatch table
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Nested definitions execute later (or in an executor); their
            # bodies are linted in their own pass if async.
            return
        if isinstance(node, ast.Await):
            self._visit(node.value)
            self._cross_await()
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            # Only reachable inside an async generator (this linter walks
            # ``async def`` bodies): ``yield`` suspends the generator, the
            # consumer — and any other task — runs before the next line.
            if node.value is not None:
                self._visit(node.value)
            self._cross_await()
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp)):
            if any(gen.is_async for gen in node.generators):
                # ``[... async for ...]`` awaits __anext__ at every
                # iteration, right here in expression position.
                for child in ast.iter_child_nodes(node):
                    self._visit(child)
                self._cross_await()
                return
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            return
        if isinstance(node, ast.AsyncFor):
            self._visit(node.iter)
            self._cross_await()
            for s in node.body + node.orelse:
                self._visit(s)
            # The hidden __anext__ await at the loop back-edge.
            self._cross_await()
            return
        if isinstance(node, ast.AsyncWith):
            locked = any(_is_lock_expr(item.context_expr) for item in node.items)
            for item in node.items:
                self._visit(item.context_expr)
            self._cross_await()
            if locked:
                self.lock_depth += 1
            for s in node.body:
                self._visit(s)
            if locked:
                self.lock_depth -= 1
            self._cross_await()
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
            # Mutating method on a shared container: self.X.pop(...), etc.
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _MUTATING_METHODS:
                attr = self._shared_attr(fn.value)
                if attr is not None:
                    for arg in node.args:
                        self._visit(arg)
                    for kw in node.keywords:
                        self._visit(kw.value)
                    self._record(_EV_WRITE, attr, node)
                    return
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            return
        if isinstance(node, ast.Expr):
            self._check_unawaited(node)
            self._visit(node.value)
            return
        if isinstance(node, ast.Assign):
            self._visit(node.value)
            for tgt in node.targets:
                self._visit_target(tgt)
            return
        if isinstance(node, ast.AugAssign):
            self._visit(node.value)
            self._visit_target(node.target, aug=True)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._visit_target(tgt)
            return
        if isinstance(node, (ast.Attribute, ast.Name)):
            attr = self._shared_attr(node)
            if attr is not None and isinstance(getattr(node, "ctx", None), ast.Load):
                self._record(_EV_READ, attr, node)
            for child in ast.iter_child_nodes(node):
                self._visit(child)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _visit_target(self, tgt: ast.AST, aug: bool = False) -> None:
        """Assignment/deletion targets: writes to shared containers."""
        if isinstance(tgt, ast.Subscript):
            attr = self._shared_attr(tgt.value)
            self._visit(tgt.slice)
            if attr is not None:
                self._record(_EV_WRITE, attr, tgt)
                return
            self._visit(tgt.value)
            return
        attr = self._shared_attr(tgt)
        if attr is not None:
            # Rebinding the container itself (or +=) clobbers concurrent
            # mutations outright.
            self._record(_EV_WRITE, attr, tgt)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._visit_target(elt, aug=aug)
            return
        for child in ast.iter_child_nodes(tgt):
            self._visit(child)

    # -- individual call rules ----------------------------------------------

    def _check_call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        matched = False
        if name is not None:
            tail2 = ".".join(name.split(".")[-2:])
            if name in _BLOCKING_CALLS or tail2 in _BLOCKING_CALLS:
                matched = True
                self._emit(
                    node,
                    RULE_BLOCKING,
                    f"blocking call {tail2}() inside async def "
                    f"{self.fn.name!r} stalls the event loop; use the async "
                    "equivalent or loop.run_in_executor()",
                )
        if (
            not matched
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_METHODS
        ):
            self._emit(
                node,
                RULE_BLOCKING,
                f"blocking file I/O .{node.func.attr}() inside async def "
                f"{self.fn.name!r} stalls the event loop; use the async "
                "equivalent or loop.run_in_executor()",
            )
        if isinstance(node.func, ast.Name) and node.func.id in _BLOCKING_BUILTINS:
            self._emit(
                node,
                RULE_BLOCKING,
                f"synchronous file I/O ({node.func.id}()) inside async def "
                f"{self.fn.name!r}; wrap in loop.run_in_executor() or move "
                "off the hot path",
            )

    def _check_unawaited(self, node: ast.Expr) -> None:
        call = node.value
        if not isinstance(call, ast.Call):
            return
        fn = call.func
        is_async = False
        label = None
        if isinstance(fn, ast.Name):
            is_async = fn.id in self.index.module_async
            label = fn.id
        elif (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"
            and self.class_name is not None
        ):
            is_async = fn.attr in self.index.class_async.get(self.class_name, set())
            label = "self." + fn.attr
        if is_async:
            self._emit(
                node,
                RULE_UNAWAITED,
                f"coroutine {label}() is never awaited — the call builds a "
                "coroutine object and drops it (add await, or rpc.spawn() "
                "for fire-and-forget)",
            )


class _CreateTaskLinter(ast.NodeVisitor):
    """raw-create-task applies everywhere (sync helpers schedule tasks too)."""

    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func) or ""
        if name.endswith(".create_task") or name in (
            "asyncio.ensure_future",
            "ensure_future",
        ):
            self.findings.append(
                Finding(
                    self.path,
                    node.lineno,
                    node.col_offset,
                    RULE_CREATE_TASK,
                    "raw create_task/ensure_future: the loop keeps only a "
                    "weak reference and the task can be GC'd mid-flight; "
                    "use ray_tpu._private.rpc.spawn() (see rpc.py)",
                )
            )
        self.generic_visit(node)


def lint_source(
    source: str, path: str = "<string>", apply_suppressions: bool = True
) -> List[Finding]:
    """Lint one module's source text; returns unsuppressed findings.

    ``apply_suppressions=False`` returns the raw findings with the
    ``# aio-lint: disable=`` comments ignored — the stale-suppression audit
    in ``devtools.lint`` uses this to decide which comments still earn
    their keep."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "parse-error", str(e.msg))]
    index = _ModuleIndex(tree)
    findings: List[Finding] = []

    ct = _CreateTaskLinter(path)
    ct.visit(tree)
    findings.extend(ct.findings)

    # Every async function, with its enclosing class (one level: the control
    # plane doesn't nest classes).
    def walk_functions(body, class_name):
        for node in body:
            if isinstance(node, ast.AsyncFunctionDef):
                findings.extend(
                    _AsyncFnLinter(node, index, class_name, path).run()
                )
                walk_functions(node.body, class_name)
            elif isinstance(node, ast.FunctionDef):
                walk_functions(node.body, class_name)
            elif isinstance(node, ast.ClassDef):
                walk_functions(node.body, node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, field, None) or []
                    for item in sub:
                        if isinstance(item, ast.ExceptHandler):
                            walk_functions(item.body, class_name)
                    if sub and not isinstance(sub[0], ast.ExceptHandler):
                        walk_functions(sub, class_name)

    walk_functions(tree.body, None)

    sup = _suppressions(source) if apply_suppressions else {}

    def suppressed(f: Finding) -> bool:
        for line in (f.line, f.line - 1):
            rules = sup.get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return sorted(
        (f for f in findings if not suppressed(f)),
        key=lambda f: (f.line, f.col, f.rule),
    )


def lint_file(path: str, apply_suppressions: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path, apply_suppressions=apply_suppressions)


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Iterable[str], apply_suppressions: bool = True
) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for f in iter_py_files(path):
                findings.extend(lint_file(f, apply_suppressions=apply_suppressions))
        else:
            findings.extend(lint_file(path, apply_suppressions=apply_suppressions))
    return findings


# ---------------------------------------------------------------------------
# Shared-attribute footprints (consumed by devtools.explore for DPOR)
# ---------------------------------------------------------------------------


def _fn_footprint(
    fn: ast.AST,
    class_name: Optional[str],
    index: _ModuleIndex,
    modbase: str,
) -> Tuple[Set[str], Set[str], Set[str]]:
    """(reads, writes, callee-qualnames) of one function over shared state.

    Tracks EVERY ``self.<attr>`` access (not just attributes the linter
    recognises as shared containers — an incomplete footprint would let the
    explorer's independence oracle judge truly conflicting events
    independent, i.e. unsound pruning) plus module-level shared containers.
    Deliberately over-approximate: any method call on a tracked attribute
    counts as a write, and nested defs are folded in — a too-big footprint
    only costs pruning, a too-small one would hide interleavings."""
    reads: Set[str] = set()
    writes: Set[str] = set()
    callees: Set[str] = set()

    def shared_key(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and class_name is not None
        ):
            # Keyed by bare attribute name, NOT Cls.attr: a base-class
            # method and a subclass method touch the SAME ``self._x`` slot,
            # and class-prefixed keys would judge them independent. Merging
            # same-named attrs across unrelated classes is the safe
            # direction (costs pruning, never soundness).
            return f"self.{node.attr}"
        if isinstance(node, ast.Name) and node.id in index.module_shared:
            return f"{modbase}:{node.id}"
        return None

    for node in ast.walk(fn):
        if isinstance(node, (ast.Attribute, ast.Name)):
            key = shared_key(node)
            if key is not None:
                if isinstance(getattr(node, "ctx", None), (ast.Store, ast.Del)):
                    writes.add(key)
                else:
                    reads.add(key)
        elif isinstance(node, ast.Subscript):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                key = shared_key(node.value)
                if key is not None:
                    writes.add(key)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                key = shared_key(f.value)
                if key is not None:
                    writes.add(key)
                if (
                    isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and class_name is not None
                ):
                    callees.add(f"{class_name}.{f.attr}")
            elif isinstance(f, ast.Name):
                callees.add(f.id)
    return reads, writes, callees


def extract_footprints(
    paths: Optional[List[str]] = None,
) -> Dict[str, Dict[str, Set[str]]]:
    """Static read/write footprints over shared containers, per function.

    Returns ``{qualname: {"reads": set, "writes": set}}`` where qualname is
    ``Cls.method`` for methods and the bare name for module functions, and
    footprint keys are ``self.attr`` / ``module:global``. Effects of callees
    reachable through ``self.x()`` and same-module function calls are folded
    in transitively (fixpoint); same qualnames across modules merge by
    union. Sync functions are included — a loop callback need not be a
    coroutine.
    """
    paths = paths or [_default_root()]
    raw: Dict[str, Dict[str, Set[str]]] = {}

    def fold(qual: str, reads: Set[str], writes: Set[str], callees: Set[str]) -> None:
        ent = raw.setdefault(
            qual, {"reads": set(), "writes": set(), "callees": set()}
        )
        ent["reads"] |= reads
        ent["writes"] |= writes
        ent["callees"] |= callees

    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(iter_py_files(path))
        else:
            files.append(path)
    for fpath in files:
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=fpath)
        except (OSError, SyntaxError):
            continue
        index = _ModuleIndex(tree)
        modbase = os.path.splitext(os.path.basename(fpath))[0]
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fold(node.name, *_fn_footprint(node, None, index, modbase))
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fold(
                            f"{node.name}.{item.name}",
                            *_fn_footprint(item, node.name, index, modbase),
                        )

    # Transitive closure over the intra-repo call graph.
    changed = True
    while changed:
        changed = False
        for ent in raw.values():
            for callee in list(ent["callees"]):
                sub = raw.get(callee)
                if sub is None:
                    continue
                before = len(ent["reads"]) + len(ent["writes"]) + len(ent["callees"])
                ent["reads"] |= sub["reads"]
                ent["writes"] |= sub["writes"]
                ent["callees"] |= sub["callees"]
                if len(ent["reads"]) + len(ent["writes"]) + len(ent["callees"]) != before:
                    changed = True
    return {
        qual: {"reads": ent["reads"], "writes": ent["writes"]}
        for qual, ent in raw.items()
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.aio_lint",
        description="asyncio hazard linter (see module docstring for rules)",
    )
    parser.add_argument("paths", nargs="*", default=None)
    args = parser.parse_args(argv)
    paths = args.paths or [_default_root()]
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"aio-lint: {len(findings)} finding(s)")
        return 1
    print("aio-lint: clean")
    return 0


def _default_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.abspath(ray_tpu.__file__))


if __name__ == "__main__":
    sys.exit(main())
