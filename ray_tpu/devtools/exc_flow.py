"""Whole-program exception-propagation analyzer for the RPC control plane.

Yuan et al. (OSDI '14, "Simple Testing Can Prevent Most Critical
Failures") found that the majority of catastrophic distributed-system
failures trace to trivially mishandled error paths: a swallowed exception,
a retry of a non-idempotent op, an ack written before the state it acks.
This pass — the eighth in the unified lint gate — closes that axis
statically. It reuses rpc_check's cached wire Inventory (handler
registrations + call sites, parsed once per gate run) and the same
handler-closure BFS shape as rpc_flow to compute, per registered RPC
handler, the interprocedural set of typed errors that can escape the
handler, and checks four contracts the runtime's correctness story leans
on: the ``wire.py`` ``errors=`` declarations, the control-error taxonomy
(CancelledError / DeadlineExceeded / StaleLeaderError must never be
silently eaten), the ``RETRY_SAFE``/``RETRY_DEDUP`` idempotence promises,
and the GCS's persist-before-ack ordering.

Rules
-----
- ``error-wire-undeclared``: a typed error (the ``wire.KNOWN_ERRORS``
  taxonomy — the RayTpuError family plus the re-typed RpcError control
  errors) can escape a registered handler whose method has a ``WireSchema``
  that does not declare it in ``errors=``. An undeclared escape crosses the
  wire as an untyped ``RpcError`` string, losing the fencing/recovery
  semantics callers dispatch on (``except StaleLeaderError`` never fires).
  Escape sets are interprocedural over the same-module call closure, with
  try/except filtering: a raise caught by a matching clause (and not
  re-raised) does not escape. Two extra-lingual facts feed the analysis:
  ``*.store.put``/``*.store.delete`` in GCS-service files can raise
  ``StaleLeaderError`` (replicated-store fencing, gcs_store.py), and a
  nested RPC call can re-raise whatever its target method *declares* of
  the re-typed set (cross-service propagation through the registry).
- ``swallowed-control-error``: a broad/bare ``except`` that eats a
  control-flow error with no re-raise. Two shapes: (a) ``except:`` or
  ``except BaseException:`` around an ``await`` in any async function of
  runtime code — that swallows ``CancelledError``, making teardown
  cancellation a silent no-op (the task becomes unkillable); (b) any broad
  clause on a *handler path* where ``DeadlineExceeded``/``StaleLeaderError``
  can flow out of the try body — that converts fencing and deadline
  signals into silent success. A clause whose body re-raises (bare
  ``raise``, or ``raise e`` of the bound name) is exempt; so is an earlier
  dedicated clause that catches the control error first.
- ``retry-unsafe-mutation``: a handler whose method is declared
  ``RETRY_SAFE`` mutates non-keyed state somewhere in its closure — an
  append/extend/insert on a shared container, or a counter
  ``+=``/``-=`` — so a transparent retry after a lost reply double-applies
  (keyed writes ``d[k] = v``, idempotent ``set.add``, and observability
  counters are exempt). ``RETRY_DEDUP`` handlers get the same finding for
  mutations sequenced *before* the first read of the schema's
  ``dedup_key`` (the dedup ledger can only mirror outcomes it has seen;
  state mutated before the key check double-applies on re-delivery).
- ``ack-before-persist``: in the GCS (gcs.py / gcs_ha.py), a reply
  (``return {...}``), waiter completion (``fut.set_result`` /
  ``set_exception``), or pubsub publish sequenced after a mutation of a
  durable table (actors / named / kv / jobs / pgs) but before the
  ``store.put`` / ``_persist_*`` write-through for that table. A crash in
  the window acks state the restarted GCS will not reload — the static
  complement of explore's ``--crash-points`` scan, which only samples the
  schedules it is given.

Static horizon: callee resolution is same-module (``self._foo()`` and
module-level ``foo()``), matching rpc_flow; cross-module escapes flow only
through the two declared facts above. The ack-before-persist ordering is
line-linear within one function — branch-crossing false positives are
possible and get a justified waiver.

Suppression: ``# exc-flow: disable=<rule>[,<rule>]`` (or ``disable=all``)
on the flagged line or the line directly above it. The unified lint gate's
stale-suppression audit covers this family.

Run: ``python -m ray_tpu.devtools.exc_flow [--report] [--mutate NAME
[--expect-violation]] [paths]``. ``--report`` prints the per-handler
escape-set table (triage aid); ``--mutate swallow_cancel`` overlays a
seeded except-swallow of CancelledError in the raylet grant path and
``--expect-violation`` inverts the exit status so CI proves the pass has
teeth.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
import textwrap
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools import rpc_check
from ray_tpu.devtools.aio_lint import Finding, _default_root, _dotted
from ray_tpu.devtools.rpc_flow import _service_for

RULE_UNDECLARED = "error-wire-undeclared"
RULE_SWALLOW = "swallowed-control-error"
RULE_RETRY = "retry-unsafe-mutation"
RULE_ACK = "ack-before-persist"

ALL_RULES = (RULE_UNDECLARED, RULE_SWALLOW, RULE_RETRY, RULE_ACK)

_SUPPRESS_RE = re.compile(r"#\s*exc-flow:\s*disable=([\w\-, ]+)")

# ---------------------------------------------------------------------------
# Typed-error taxonomy (mirrors common.py + rpc.py class hierarchies; kept
# static so fixture trees need no imports). ``wire.KNOWN_ERRORS`` is the
# declarable subset.
# ---------------------------------------------------------------------------

_PARENTS: Dict[str, str] = {
    "TaskError": "RayTpuError",
    "WorkerCrashedError": "RayTpuError",
    "ActorDiedError": "RayTpuError",
    "ActorUnavailableError": "RayTpuError",
    "ObjectLostError": "RayTpuError",
    "ObjectReconstructionFailedError": "ObjectLostError",
    "GetTimeoutError": "RayTpuError",
    "TaskCancelledError": "RayTpuError",
    "PlacementGroupError": "RayTpuError",
    "CollectiveGroupDiedError": "RayTpuError",
    "RayTpuError": "Exception",
    "ConnectionLost": "RpcError",
    "DeadlineExceeded": "RpcError",
    "StaleLeaderError": "RpcError",
    "RpcError": "Exception",
    "TimeoutError": "Exception",
    "Exception": "BaseException",
    "CancelledError": "BaseException",
}

# Control-flow errors whose silent swallow breaks cancellation/fencing.
_CONTROL = ("CancelledError", "DeadlineExceeded", "StaleLeaderError")

# The subset that crosses the wire *typed* (rpc._TYPED_ERRORS re-types the
# error-reply string): only these propagate through nested RPC call sites.
_WIRE_TYPED = frozenset({"StaleLeaderError", "DeadlineExceeded"})


def _ancestors(name: str) -> Set[str]:
    out: Set[str] = set()
    cur = name
    while cur in _PARENTS:
        cur = _PARENTS[cur]
        out.add(cur)
    if name == "GetTimeoutError":  # multiple inheritance (common.py)
        out.add("TimeoutError")
    return out


_ANCESTORS: Dict[str, Set[str]] = {n: _ancestors(n) for n in _PARENTS}


def _covers(caught: Set[str], err: str) -> bool:
    """Does an except clause naming ``caught`` classes catch ``err``?"""
    return bool(
        caught & ({err} | _ANCESTORS.get(err, set()))
    )


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _catch_set(handler: ast.ExceptHandler) -> Set[str]:
    """Trailing class names an except clause catches (bare = BaseException)."""
    t = handler.type
    if t is None:
        return {"BaseException"}
    if isinstance(t, ast.Tuple):
        return {n for n in (_tail(e) for e in t.elts) if n}
    n = _tail(t)
    return {n} if n else set()


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Bare ``raise`` (or ``raise e`` of the bound name) in the clause body."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            if (
                handler.name
                and isinstance(node.exc, ast.Name)
                and node.exc.id == handler.name
            ):
                return True
    return False


_SPAWN_NAMES = {"spawn", "_spawn"}

# Receiver-chain segments that mark observability state: mutations of
# counters/stat dicts/flight-recorder events are not retry hazards (they
# skew a metric, not the control plane).
_OBS_TOKENS = ("stats", "telemetry", "_tel", "events", "metrics", "tracing")

# Non-idempotent container adds (list semantics). ``set.add``/``discard``
# and keyed dict writes are idempotent and exempt.
_APPEND_VERBS = {"append", "extend", "insert", "appendleft"}

# ---------------------------------------------------------------------------
# GCS durability model (ack-before-persist).
# ---------------------------------------------------------------------------

_GCS_SUFFIXES = ("_private/gcs.py", "_private/gcs_ha.py")

# In-memory attribute -> canonical durable-table id (store table names).
_DURABLE_ATTRS = {
    "actors": "actors",
    "named_actors": "named",
    "kv": "kv",
    "jobs": "jobs",
    "placement_groups": "pgs",
}
# Conventional aliases for records pulled out of (or passed alongside) a
# durable table: ``actor.state = DEAD`` mutates the actors table.
_ALIAS_NAMES = {"actor": "actors", "pg": "pgs", "job": "jobs"}
_PERSIST_FNS = {
    "_persist_actor": "actors",
    "_persist_named": "named",
    "_persist_kv": "kv",
    "_persist_job": "jobs",
    "_persist_pg": "pgs",
}
_STORE_TABLES = {"actors", "named", "kv", "jobs", "pgs"}
# Record attributes that are NOT persisted (in-memory bookkeeping riding
# the same record objects): mutating them needs no write-through.
_EPHEMERAL_REC_ATTRS = {"pending", "fut", "future", "waiters", "conn"}


def _is_gcs_file(path: str) -> bool:
    norm = os.path.abspath(path).replace(os.sep, "/")
    return norm.endswith(_GCS_SUFFIXES)


def _in_runtime_scope(path: str) -> bool:
    return "_private" in os.path.abspath(path).split(os.sep)


# ---------------------------------------------------------------------------
# Module scan: function table with callee resolution (rpc_flow's shape,
# keeping the AST nodes for the escape walk).
# ---------------------------------------------------------------------------


def _local_nodes(fn: ast.AST):
    """Walk a function body without descending into nested defs/lambdas."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _ModuleFns:
    """Qualname -> function AST for one module, with same-module callee
    resolution (``self._foo()`` against the enclosing class, bare ``foo()``
    against module level)."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.service = _service_for(path)
        self.fns: Dict[str, ast.AST] = {}
        self.by_name: Dict[str, List[str]] = {}
        self._walk(tree.body, prefix="")

    def _walk(self, body, prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._walk(node.body, prefix=f"{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                if qual in self.fns:  # redefinition: keep the last
                    self.by_name[node.name].remove(qual)
                self.fns[qual] = node
                self.by_name.setdefault(node.name, []).append(qual)

    def resolve(self, name: str, cls: Optional[str]) -> Optional[str]:
        if cls is not None and f"{cls}.{name}" in self.fns:
            return f"{cls}.{name}"
        quals = self.by_name.get(name, [])
        if len(quals) == 1:
            return quals[0]
        if cls is None and name in self.fns:
            return name
        return None

    def callees(self, qual: str) -> Tuple[Set[str], Set[str]]:
        """(synchronous callees, spawned callees), resolved qualnames."""
        fn = self.fns[qual]
        cls = qual.rsplit(".", 1)[0] if "." in qual else None
        sync: Set[str] = set()
        spawned: Set[str] = set()
        spawn_args: Set[int] = set()
        for node in _local_nodes(fn):
            if (
                isinstance(node, ast.Call)
                and _tail(node.func) in _SPAWN_NAMES
                and node.args
                and isinstance(node.args[0], ast.Call)
            ):
                spawn_args.add(id(node.args[0]))
                target = _tail(node.args[0].func)
                if target:
                    nxt = self.resolve(target, cls)
                    if nxt is not None:
                        spawned.add(nxt)
        for node in _local_nodes(fn):
            if not isinstance(node, ast.Call) or id(node) in spawn_args:
                continue
            func = node.func
            name = None
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                name = func.attr
            elif isinstance(func, ast.Name):
                name = func.id
            if name is None:
                continue
            nxt = self.resolve(name, cls)
            if nxt is not None:
                sync.add(nxt)
        return sync, spawned


# ---------------------------------------------------------------------------
# Escape analysis: the set of typed error names that can escape each
# function, interprocedural (same-module fixpoint) with try/except
# filtering.
# ---------------------------------------------------------------------------


class _EscapeTable:
    def __init__(self, mod: _ModuleFns):
        self.mod = mod
        self.table: Dict[str, Set[str]] = {q: set() for q in mod.fns}
        changed = True
        while changed:
            changed = False
            for qual, fn in mod.fns.items():
                cur = self._block(list(ast.iter_child_nodes(fn)), qual)
                if cur != self.table[qual]:
                    self.table[qual] = cur
                    changed = True

    # -- per-node escape contribution ---------------------------------------

    def _node(self, node: ast.AST, qual: str) -> Set[str]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return set()
        if isinstance(node, ast.Try):
            return self._try(node, qual)
        out: Set[str] = set()
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            name = _tail(exc.func) if isinstance(exc, ast.Call) else _tail(exc)
            if name in _PARENTS and name not in ("Exception", "BaseException"):
                out.add(name)
        elif isinstance(node, ast.Call):
            out |= self._call(node, qual)
            if _tail(node.func) in _SPAWN_NAMES:
                # A spawned task's exceptions do not propagate to this
                # function — do not descend into the spawned coroutine call.
                return out
        for child in ast.iter_child_nodes(node):
            out |= self._node(child, qual)
        return out

    def _call(self, node: ast.Call, qual: str) -> Set[str]:
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        # Nested RPC: the target method's *declared* re-typed errors can
        # re-raise here (rpc._typed_error reconstructs them caller-side).
        if attr in rpc_check._CALL_METHODS and node.args:
            m = node.args[0]
            if isinstance(m, ast.Constant) and isinstance(m.value, str):
                from ray_tpu._private import wire

                schema = wire.SCHEMAS.get(m.value)
                if schema is not None:
                    return set(schema.errors) & _WIRE_TYPED
            return set()
        # Replicated-store fencing: a write through the GCS store can raise
        # StaleLeaderError (gcs_store.py) — the fact that makes every GCS
        # write-through handler escape it.
        if (
            attr in ("put", "delete")
            and self.mod.service == "gcs"
            and isinstance(func, ast.Attribute)
        ):
            recv = _dotted(func.value) or ""
            if recv.rsplit(".", 1)[-1] == "store" or recv == "store":
                return {"StaleLeaderError"}
        # Same-module callee: its current escape estimate flows through.
        cls = qual.rsplit(".", 1)[0] if "." in qual else None
        name = None
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name is not None:
            callee = self.mod.resolve(name, cls)
            if callee is not None:
                return set(self.table.get(callee, set()))
        return set()

    def _block(self, stmts, qual: str) -> Set[str]:
        out: Set[str] = set()
        for s in stmts:
            out |= self._node(s, qual)
        return out

    def _try(self, node: ast.Try, qual: str) -> Set[str]:
        body = self._block(node.body, qual)
        for h in node.handlers:
            caught = _catch_set(h)
            if not _reraises(h):
                body = {e for e in body if not _covers(caught, e)}
            body |= self._block(h.body, qual)
        body |= self._block(node.orelse, qual)
        body |= self._block(node.finalbody, qual)
        return body

    # -- what can arrive at a try's except clauses --------------------------

    def arriving(self, t: ast.Try, qual: str) -> Set[str]:
        """Typed errors the try body can deliver to the handler clauses."""
        return self._block(t.body, qual)


# ---------------------------------------------------------------------------
# Whole-program analysis container.
# ---------------------------------------------------------------------------


@dataclass
class HandlerInfo:
    service: str
    method: str
    path: str
    line: int
    qualname: Optional[str]
    closure: Set[str] = field(default_factory=set)  # quals, sync + spawned


@dataclass
class Analysis:
    scans: Dict[str, _ModuleFns] = field(default_factory=dict)
    escapes: Dict[str, _EscapeTable] = field(default_factory=dict)
    handlers: List[HandlerInfo] = field(default_factory=list)
    # (module path, qual) -> handler labels ("service:Method") whose closure
    # contains the function (sync or spawned part).
    on_handler_path: Dict[Tuple[str, str], Set[str]] = field(
        default_factory=dict
    )

    def handler_escapes(self, h: HandlerInfo) -> Set[str]:
        if h.qualname is None:
            return set()
        return set(self.escapes[h.path].table.get(h.qualname, set()))


def _collect_sources(
    paths: Sequence[str],
    extra_sources: Optional[Sequence[Tuple[str, str]]],
) -> List[Tuple[str, Optional[ast.Module]]]:
    out: List[Tuple[str, Optional[ast.Module]]] = []
    for f in rpc_check._collect_files(list(paths)):
        out.append((f, rpc_check.cached_tree(f)))
    for vpath, vsrc in extra_sources or ():
        try:
            out.append((vpath, ast.parse(textwrap.dedent(vsrc), filename=vpath)))
        except SyntaxError:
            out.append((vpath, None))
    return out


def build(
    paths: Optional[Sequence[str]] = None,
    extra_sources: Optional[Sequence[Tuple[str, str]]] = None,
) -> Analysis:
    paths = list(paths or [_default_root()])
    inv = rpc_check.cached_inventory(paths)
    if extra_sources:
        inv = rpc_check._merge_inventories(
            [inv], extra_sources=list(extra_sources)
        )

    analysis = Analysis()
    for path, tree in _collect_sources(paths, extra_sources):
        if tree is None:
            continue
        mod = _ModuleFns(path, tree)
        analysis.scans[path] = mod
        analysis.escapes[path] = _EscapeTable(mod)

    for reg in sorted(inv.regs, key=lambda r: (r.path, r.line)):
        mod = analysis.scans.get(reg.path)
        if mod is None:
            continue
        qual = None
        if reg.handler_name:
            quals = mod.by_name.get(reg.handler_name, [])
            if quals:
                qual = quals[0]
        h = HandlerInfo(
            service=mod.service,
            method=reg.method,
            path=reg.path,
            line=reg.line,
            qualname=qual,
        )
        if qual is not None:
            h.closure = _closure(mod, qual)
            label = f"{h.service}:{h.method}"
            for q in h.closure:
                analysis.on_handler_path.setdefault(
                    (reg.path, q), set()
                ).add(label)
        analysis.handlers.append(h)
    return analysis


def _closure(mod: _ModuleFns, start: str) -> Set[str]:
    """Same-module call closure (sync + spawned) of one handler."""
    seen: Set[str] = set()
    frontier = [start]
    while frontier:
        qual = frontier.pop()
        if qual in seen or qual not in mod.fns:
            continue
        seen.add(qual)
        sync, spawned = mod.callees(qual)
        frontier.extend(sync | spawned)
    return seen


# ---------------------------------------------------------------------------
# Rule: error-wire-undeclared.
# ---------------------------------------------------------------------------


def _undeclared_findings(analysis: Analysis) -> List[Finding]:
    from ray_tpu._private import wire

    findings: List[Finding] = []
    seen: Set[Tuple[str, str, Tuple[str, ...]]] = set()
    for h in analysis.handlers:
        schema = wire.SCHEMAS.get(h.method)
        if schema is None or h.qualname is None:
            continue
        escapes = analysis.handler_escapes(h) & wire.KNOWN_ERRORS
        undeclared = tuple(sorted(escapes - set(schema.errors)))
        if not undeclared:
            continue
        fn = analysis.scans[h.path].fns[h.qualname]
        key = (h.path, h.method, undeclared)
        if key in seen:
            continue
        seen.add(key)
        findings.append(
            Finding(
                h.path,
                fn.lineno,
                0,
                RULE_UNDECLARED,
                f"handler {h.qualname} for {h.method!r} can raise "
                f"{list(undeclared)} — not declared on its WireSchema "
                f"(wire.py errors={sorted(schema.errors)}); an undeclared "
                "typed error crosses the wire as an untyped RpcError and "
                "callers lose the fencing/recovery dispatch. Add it to the "
                "schema's errors= (or catch it in the handler)",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# Rule: swallowed-control-error.
# ---------------------------------------------------------------------------


def _broad_kind(h: ast.ExceptHandler) -> Optional[str]:
    if h.type is None:
        return "bare except:"
    t = _tail(h.type)
    if t == "BaseException":
        return "except BaseException"
    if t == "Exception":
        return "except Exception"
    return None


def _has_await(stmts) -> bool:
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
    return False


def _swallow_findings(analysis: Analysis) -> List[Finding]:
    findings: List[Finding] = []
    for path, mod in analysis.scans.items():
        if not _in_runtime_scope(path):
            continue
        esc = analysis.escapes[path]
        for qual, fn in mod.fns.items():
            is_async = isinstance(fn, ast.AsyncFunctionDef)
            handler_of = analysis.on_handler_path.get((path, qual), set())
            for node in _local_nodes(fn):
                if not isinstance(node, ast.Try):
                    continue
                arriving = esc.arriving(node, qual)
                cancel_can_flow = is_async and _has_await(node.body)
                remaining = set(arriving)
                cancel_remaining = cancel_can_flow
                for h in node.handlers:
                    caught = _catch_set(h)
                    kind = _broad_kind(h)
                    caught_typed = {
                        e for e in remaining if _covers(caught, e)
                    }
                    catches_cancel = cancel_remaining and _covers(
                        caught, "CancelledError"
                    )
                    if kind is not None and not _reraises(h):
                        eaten: Set[str] = set()
                        if catches_cancel and kind != "except Exception":
                            # except Exception does NOT catch
                            # CancelledError (BaseException since 3.8).
                            eaten.add("CancelledError")
                        if handler_of:
                            eaten |= caught_typed & set(_CONTROL)
                        if eaten:
                            on = (
                                " on the handler path of "
                                + ", ".join(sorted(handler_of)[:3])
                                if handler_of
                                else f" in async {qual}"
                            )
                            findings.append(
                                Finding(
                                    path,
                                    h.lineno,
                                    0,
                                    RULE_SWALLOW,
                                    f"{kind} swallows "
                                    f"{sorted(eaten)}{on} — converts a "
                                    "cancellation/fencing/deadline signal "
                                    "into silent success. Re-raise control "
                                    "errors (bare `raise`, or an isinstance "
                                    "filter) or narrow the except",
                                )
                            )
                    # Whatever this clause catches never reaches later
                    # clauses (re-raised errors escape the try entirely).
                    remaining -= caught_typed
                    if catches_cancel:
                        cancel_remaining = False
    return findings


# ---------------------------------------------------------------------------
# Rule: retry-unsafe-mutation.
# ---------------------------------------------------------------------------


def _observability(chain: str) -> bool:
    return any(
        tok in seg.lower() for seg in chain.split(".") for tok in _OBS_TOKENS
    )


def _self_rooted(node: ast.AST) -> Optional[str]:
    """Dotted chain if the expression is rooted at ``self``."""
    chain = _dotted(node)
    if chain and (chain == "self" or chain.startswith("self.")):
        return chain
    return None


def _mutation_sites(fn: ast.AST) -> List[Tuple[int, str]]:
    """Non-keyed mutations of self-rooted shared state in one function:
    counter arithmetic (AugAssign) and list-semantics adds. The verbs
    mirror aio_lint's shared-attribute write footprints, narrowed to the
    non-idempotent subset (keyed ``d[k] = v`` and ``set.add`` are fine
    under re-delivery)."""
    out: List[Tuple[int, str]] = []
    for node in _local_nodes(fn):
        if isinstance(node, ast.AugAssign):
            tgt = node.target
            base = tgt.value if isinstance(tgt, (ast.Attribute, ast.Subscript)) else None
            chain = _self_rooted(tgt) or (
                _self_rooted(base) if base is not None else None
            )
            if chain and not _observability(chain):
                out.append((node.lineno, f"{chain} {type(node.op).__name__}="))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _APPEND_VERBS
        ):
            chain = _self_rooted(node.func.value)
            if chain and not _observability(chain):
                out.append((node.lineno, f"{chain}.{node.func.attr}(...)"))
    return out


def _dedup_key_line(fn: ast.AST, key: str) -> Optional[int]:
    """First line the handler reads its dedup key (``p["k"]``/``p.get("k")``
    on the payload parameter, or any literal of the key name)."""
    args = getattr(fn, "args", None)
    pname = args.args[-1].arg if args and args.args else None
    best: Optional[int] = None
    for node in _local_nodes(fn):
        hit = False
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == pname
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == key
        ):
            hit = True
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == pname
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == key
        ):
            hit = True
        elif isinstance(node, ast.Constant) and node.value == key:
            hit = True
        if hit and (best is None or node.lineno < best):
            best = node.lineno
    return best


def _retry_findings(analysis: Analysis) -> List[Finding]:
    from ray_tpu._private import wire

    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for h in analysis.handlers:
        schema = wire.SCHEMAS.get(h.method)
        if schema is None or h.qualname is None:
            continue
        mod = analysis.scans[h.path]
        if schema.retry == wire.RETRY_SAFE:
            for qual in sorted(h.closure):
                fn = mod.fns.get(qual)
                if fn is None:
                    continue
                for line, desc in _mutation_sites(fn):
                    key = (h.path, line)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        Finding(
                            h.path,
                            line,
                            0,
                            RULE_RETRY,
                            f"RETRY_SAFE handler {h.service}:{h.method} "
                            f"mutates non-keyed state (`{desc}` in {qual}) "
                            "— a transparent retry after a lost reply "
                            "double-applies it. Make the write keyed/"
                            "idempotent, or declare the method RETRY_NONE/"
                            "RETRY_DEDUP honestly",
                        )
                    )
        elif schema.retry == wire.RETRY_DEDUP:
            fn = mod.fns.get(h.qualname)
            if fn is None:
                continue
            key_line = _dedup_key_line(fn, schema.dedup_key or "")
            own = [(ln, d, h.qualname) for ln, d in _mutation_sites(fn)]
            # Callee mutations count at their call-site line in the handler:
            # the dedup check must happen before ANY state moves.
            cls = (
                h.qualname.rsplit(".", 1)[0] if "." in h.qualname else None
            )
            mutating_callees = {
                q
                for q in h.closure
                if q != h.qualname
                and mod.fns.get(q) is not None
                and _mutation_sites(mod.fns[q])
            }
            for node in _local_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = None
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "self"
                ):
                    name = func.attr
                elif isinstance(func, ast.Name):
                    name = func.id
                if name is None:
                    continue
                callee = mod.resolve(name, cls)
                if callee in mutating_callees:
                    own.append(
                        (node.lineno, f"{name}(...) [mutating callee]", callee)
                    )
            for line, desc, where in own:
                if key_line is not None and line >= key_line:
                    continue  # after the dedup-key check: ledger covers it
                key = (h.path, line)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(
                    Finding(
                        h.path,
                        line,
                        0,
                        RULE_RETRY,
                        f"RETRY_DEDUP handler {h.service}:{h.method} "
                        f"mutates state (`{desc}`) before reading its "
                        f"dedup key {schema.dedup_key!r}"
                        + (
                            f" (first read at line {key_line})"
                            if key_line is not None
                            else " (never read in the handler)"
                        )
                        + " — a re-delivered frame double-applies the "
                        "mutation before the ledger can mirror the "
                        "original outcome. Check the dedup key first",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# Rule: ack-before-persist.
# ---------------------------------------------------------------------------


def _ack_findings(analysis: Analysis) -> List[Finding]:
    findings: List[Finding] = []
    handler_quals = {
        (h.path, h.qualname)
        for h in analysis.handlers
        if h.qualname is not None
    }
    for path, mod in analysis.scans.items():
        if not _is_gcs_file(path):
            continue
        # Per-fn: which durable tables its closure persists (for clearing
        # dirt at helper-call sites).
        persists_of: Dict[str, Set[str]] = {}
        for qual, fn in mod.fns.items():
            persists_of[qual] = _direct_persists(fn)
        for qual in mod.fns:
            closure = _closure(mod, qual)
            merged = set()
            for q in closure:
                merged |= persists_of.get(q, set())
            persists_of[qual] = merged
        for qual, fn in mod.fns.items():
            findings.extend(
                _scan_fn_ordering(
                    mod,
                    qual,
                    fn,
                    persists_of,
                    is_handler=(path, qual) in handler_quals,
                )
            )
    return findings


def _direct_persists(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in _local_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        t = _tail(node.func)
        if t in _PERSIST_FNS:
            out.add(_PERSIST_FNS[t])
        elif (
            t in ("put", "delete")
            and isinstance(node.func, ast.Attribute)
            and (_dotted(node.func.value) or "").rsplit(".", 1)[-1] == "store"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value in _STORE_TABLES
        ):
            out.add(node.args[0].value)
    return out


def _scan_fn_ordering(
    mod: _ModuleFns,
    qual: str,
    fn: ast.AST,
    persists_of: Dict[str, Set[str]],
    is_handler: bool = True,
) -> List[Finding]:
    """Line-linear mutate → persist → ack ordering within one function."""
    cls = qual.rsplit(".", 1)[0] if "." in qual else None
    aliases: Dict[str, str] = {}
    args = getattr(fn, "args", None)
    if args is not None:
        for a in args.args:
            if a.arg in _ALIAS_NAMES:
                aliases[a.arg] = _ALIAS_NAMES[a.arg]

    def durable_of(node: ast.AST) -> Optional[str]:
        """Durable table a reference resolves to (self.<attr> or alias)."""
        if isinstance(node, ast.Attribute):
            root = _dotted(node) or ""
            if root.startswith("self.") :
                attr = root.split(".", 2)[1] if root.count(".") >= 1 else ""
                if attr in _DURABLE_ATTRS:
                    return _DURABLE_ATTRS[attr]
        if isinstance(node, ast.Name) and node.id in aliases:
            return aliases[node.id]
        return None

    # events: (line, col, kind, payload)
    events: List[Tuple[int, int, str, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            continue
        # alias binding: actor = self.actors[...] / .get(...) / .pop(...)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            src = node.value
            base = None
            if isinstance(src, ast.Subscript):
                base = src.value
            elif (
                isinstance(src, ast.Call)
                and isinstance(src.func, ast.Attribute)
                and src.func.attr in ("get", "pop", "setdefault")
            ):
                base = src.func.value
            if (
                base is not None
                and isinstance(tgt, ast.Name)
                and isinstance(base, ast.Attribute)
            ):
                root = _dotted(base) or ""
                attr = root.split(".")[1] if root.startswith("self.") else ""
                if attr in _DURABLE_ATTRS:
                    aliases[tgt.id] = _DURABLE_ATTRS[attr]
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            it = node.iter
            base = None
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute):
                base = it.func.value
                if (
                    isinstance(base, ast.Call)
                    and isinstance(base.func, ast.Name)
                    and base.func.id == "list"
                    and base.args
                ):
                    inner = base.args[0]
                    if isinstance(inner, ast.Call) and isinstance(
                        inner.func, ast.Attribute
                    ):
                        base = inner.func.value
            if isinstance(base, ast.Attribute):
                root = _dotted(base) or ""
                attr = root.split(".")[1] if root.startswith("self.") else ""
                if attr in _DURABLE_ATTRS:
                    aliases[node.target.id] = _DURABLE_ATTRS[attr]

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            continue
        line, col = getattr(node, "lineno", 0), getattr(node, "col_offset", 0)
        # -- mutations --
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in tgts:
                if isinstance(tgt, ast.Subscript):
                    t = durable_of(tgt.value)
                    if t:
                        events.append((line, col, "mut", t))
                elif isinstance(tgt, ast.Attribute):
                    if tgt.attr in _EPHEMERAL_REC_ATTRS:
                        continue
                    t = durable_of(tgt.value)
                    if t:
                        events.append((line, col, "mut", t))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    t = durable_of(tgt.value)
                    if t:
                        events.append((line, col, "mut", t))
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            verb = node.func.attr
            if verb in ("pop", "update", "clear", "setdefault", "append"):
                t = durable_of(node.func.value)
                if t:
                    events.append((line, col, "mut", t))
        # -- persists (direct + via helper call) --
        if isinstance(node, ast.Call):
            t = _tail(node.func)
            if t in _PERSIST_FNS:
                events.append((line, col, "persist", _PERSIST_FNS[t]))
            elif (
                t in ("put", "delete")
                and isinstance(node.func, ast.Attribute)
                and (_dotted(node.func.value) or "").rsplit(".", 1)[-1]
                == "store"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in _STORE_TABLES
            ):
                events.append((line, col, "persist", node.args[0].value))
            elif t is not None:
                callee = mod.resolve(t, cls)
                if callee is not None and callee != qual:
                    for table in sorted(persists_of.get(callee, ())):
                        events.append((line, col, "persist", table))
        # -- acks --
        # A ``return`` is a wire reply only in a registered handler; a
        # helper returning a value to the scheduler loop acks nothing.
        if (
            is_handler
            and isinstance(node, ast.Return)
            and node.value is not None
        ):
            if not (
                isinstance(node.value, ast.Constant)
                and node.value.value is None
            ):
                events.append((line, col, "ack", "reply (return)"))
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            a = node.func.attr
            recv = _dotted(node.func.value) or ""
            if a in ("set_result", "set_exception"):
                events.append((line, col, "ack", f"waiter {a}"))
            elif a == "_publish_msg" or (
                a == "publish" and "publisher" in recv
            ):
                events.append((line, col, "ack", "publish"))

    events.sort(key=lambda e: (e[0], e[1]))
    dirty: Dict[str, int] = {}
    findings: List[Finding] = []
    reported: Set[int] = set()
    for line, _col, kind, payload in events:
        if kind == "mut":
            dirty.setdefault(payload, line)
        elif kind == "persist":
            dirty.pop(payload, None)
        elif kind == "ack" and dirty and line not in reported:
            reported.add(line)
            tables = ", ".join(
                f"{t} (mutated line {ln})" for t, ln in sorted(dirty.items())
            )
            findings.append(
                Finding(
                    mod.path,
                    line,
                    0,
                    RULE_ACK,
                    f"{payload} in {qual} is reachable before the "
                    f"write-through for durable table(s) {tables} — a crash "
                    "in the window acks state the restarted GCS will not "
                    "reload. Persist first, then reply/publish",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Mutation gate: a seeded except-swallow of CancelledError in the raylet
# grant path. The overlay path ends in _private/raylet.py so scope rules
# attribute it to the runtime; --expect-violation requires the pass to
# flag it with its own rule (the rpc_flow/explore --mutate pattern).
# ---------------------------------------------------------------------------

# name -> (virtual overlay path, overlay source, rule the gate must raise)
_MUTATIONS: Dict[str, Tuple[str, str, str]] = {
    "swallow_cancel": (
        "<mutant>/_private/raylet.py",
        """
        class _MutantRaylet:
            def _register_handlers(self, s):
                s.register(
                    "RequestWorkerLease", self._request_worker_lease_mutant
                )

            async def _request_worker_lease_mutant(self, conn, p):
                try:
                    return await self._grant_lease(p)
                except BaseException:
                    # Swallows CancelledError during teardown: the grant
                    # task reports success instead of unwinding.
                    return {"ok": True}

            async def _grant_lease(self, p):
                await self.pool.ready()
                return {"granted": True}
        """,
        RULE_SWALLOW,
    ),
}


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def check(
    paths: Optional[Sequence[str]] = None,
    apply_suppressions: bool = True,
    mutate: Optional[str] = None,
) -> List[Finding]:
    extra = None
    if mutate is not None:
        if mutate not in _MUTATIONS:
            raise SystemExit(
                f"unknown mutation {mutate!r} (have: {sorted(_MUTATIONS)})"
            )
        vpath, vsrc, _ = _MUTATIONS[mutate]
        extra = [(vpath, vsrc)]
    analysis = build(paths, extra_sources=extra)
    findings = (
        _undeclared_findings(analysis)
        + _swallow_findings(analysis)
        + _retry_findings(analysis)
        + _ack_findings(analysis)
    )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if not apply_suppressions:
        return findings

    sup_cache: Dict[str, Dict[int, Set[str]]] = {}

    def suppressed(f: Finding) -> bool:
        if f.path not in sup_cache:
            sup: Dict[int, Set[str]] = {}
            try:
                with open(f.path, "r", encoding="utf-8") as fh:
                    for i, text in enumerate(fh.read().splitlines(), 1):
                        m = _SUPPRESS_RE.search(text)
                        if m:
                            sup[i] = {
                                r.strip()
                                for r in m.group(1).split(",")
                                if r.strip()
                            }
            except OSError:
                pass
            sup_cache[f.path] = sup
        for line in (f.line, f.line - 1):
            rules = sup_cache[f.path].get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return [f for f in findings if not suppressed(f)]


def report(paths: Optional[Sequence[str]] = None) -> str:
    """Per-handler escape-set table (triage aid for errors= declarations)."""
    from ray_tpu._private import wire

    analysis = build(paths)
    lines = ["handler escape sets (typed taxonomy only):", ""]
    for h in sorted(analysis.handlers, key=lambda h: (h.service, h.method)):
        if h.qualname is None:
            continue
        esc = analysis.handler_escapes(h) & wire.KNOWN_ERRORS
        schema = wire.SCHEMAS.get(h.method)
        declared = sorted(schema.errors) if schema else None
        lines.append(
            f"  {h.service}:{h.method}  escapes={sorted(esc) or '[]'}  "
            f"declared={declared if declared is not None else '(no schema)'}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.exc_flow",
        description="whole-program exception-propagation analyzer",
    )
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument(
        "--report",
        action="store_true",
        help="print the per-handler escape-set table instead of checking",
    )
    parser.add_argument(
        "--mutate",
        default=None,
        help=f"overlay a seeded defect (have: {sorted(_MUTATIONS)})",
    )
    parser.add_argument(
        "--expect-violation",
        action="store_true",
        help="invert the exit status: succeed only if findings were raised",
    )
    args = parser.parse_args(argv)
    paths = args.paths or None
    if args.report:
        print(report(paths))
        return 0
    findings = check(paths, mutate=args.mutate)
    for f in findings:
        print(f)
    if args.expect_violation:
        # The seeded defect must raise its *own* rule — pre-existing
        # findings of other rules must not make a toothless pass look
        # sharp.
        want = (
            _MUTATIONS[args.mutate][2] if args.mutate in _MUTATIONS else None
        )
        hits = [f for f in findings if want is None or f.rule == want]
        if hits:
            print(
                f"exc-flow: mutation detected ({len(hits)} "
                f"{want or 'any'} finding(s)) — the pass has teeth"
            )
            return 0
        print(
            f"exc-flow: expected a {want or 'violation'} finding "
            "but found none"
        )
        return 1
    if findings:
        print(f"exc-flow: {len(findings)} finding(s)")
        return 1
    print("exc-flow: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
