"""Control-plane protocol state machines: spec + static checker + docs.

The control plane is a bundle of small FSMs — GCS actor states, placement
groups, node liveness, the raylet's granted-lease ledger — and the chaos
runs in PR 2 showed that the expensive bugs are illegal *edges*: a node
marked DEAD resurrecting, a restarted GCS persisting a bogus state, a
lease released twice. This module declares each machine as data
(:class:`Machine`: states, legal transitions, terminal states, and which
component drives each edge), and the checker statically extracts every
``<recv>.state = X`` / ``<recv>["state"] = X`` assignment in ``gcs.py`` /
``raylet.py`` / ``core_worker.py`` and verifies it against the spec.

Rules
-----
- ``protocol-unknown-state``: an assignment or comparison resolves to a
  string that is not a declared state of the receiver's machine (typo, or
  the spec is stale).
- ``protocol-illegal-transition``: an assignment that cannot be a legal
  edge — in ``__init__`` it must be an initial state; under a
  ``if recv.state == SRC`` (or ``in (SRC, ...)``) guard the edge
  ``SRC -> dst`` must be declared; unguarded, ``dst`` must be an initial
  state or have at least one declared incoming edge.
- ``protocol-unresolvable``: the assigned value is dynamic (not a literal
  or module-level constant). Restart-restore paths are the legitimate
  case; suppress them with a justification.
- ``protocol-invariant-drift``: the actor machine's quiescent states and
  ``ray_tpu.chaos.invariants.TERMINAL_ACTOR_STATES`` disagree — the
  static spec and the chaos convergence invariants must never drift.

Resolution is symbolic: module-level ``NAME = "LITERAL"`` constants in the
scanned file are followed, so ``gcs.py`` keeping its states in constants
is what makes the pass precise (see the normalization in that module).
Receivers are mapped to machines by class (``self.state`` inside
``ActorInfo``), by conventional variable name (``actor``/``node``/``pg``),
or by subscript variable for wire dicts (``info["state"]``); the
``granted_lease_ids[...] = True/False`` ledger writes map booleans to the
LIVE/RELEASED states. Unmapped receivers are out of scope.

``--markdown`` regenerates ``docs/protocols.md`` (tables + mermaid
diagrams) from the same spec, so the docs cannot drift either (CI diffs
the checked-in copy).

Suppression: ``# protocol: disable=<rule>[,<rule>]`` on the flagged line
or the line directly above it.

Run: ``python -m ray_tpu.devtools.protocols [--markdown] [paths]``.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.aio_lint import (
    Finding,
    _default_root,
    _dotted,
    iter_py_files,
)

RULE_UNKNOWN = "protocol-unknown-state"
RULE_ILLEGAL = "protocol-illegal-transition"
RULE_UNRESOLVABLE = "protocol-unresolvable"
RULE_DRIFT = "protocol-invariant-drift"

ALL_RULES = (RULE_UNKNOWN, RULE_ILLEGAL, RULE_UNRESOLVABLE, RULE_DRIFT)

_SUPPRESS_RE = re.compile(r"#\s*protocol:\s*disable=([\w\-, ]+)")


@dataclass(frozen=True)
class Machine:
    """One protocol FSM, declared as data.

    ``classes`` maps ``self.state`` assignments inside those class bodies;
    ``variables`` maps ``<name>.state`` receivers; ``subscript_vars`` maps
    ``<name>["state"]`` wire-dict receivers. ``quiescent`` is the set of
    states that may legitimately persist once the cluster has settled
    (cross-checked against the chaos invariants for the actor machine).
    ``transitions`` is ``(src, dst, driver)`` where driver names the
    component allowed to take the edge.
    """

    name: str
    doc: str
    classes: Tuple[str, ...]
    variables: Tuple[str, ...]
    subscript_vars: Tuple[str, ...]
    files: Tuple[str, ...]
    states: Tuple[str, ...]
    initial: Tuple[str, ...]
    terminal: Tuple[str, ...]
    quiescent: Tuple[str, ...]
    transitions: Tuple[Tuple[str, str, str], ...]


ACTOR = Machine(
    name="actor",
    doc="GCS actor FSM (ray_tpu/_private/gcs.py, reference: "
    "gcs_actor_manager.cc)",
    classes=("ActorInfo",),
    variables=("actor", "existing", "existing_self", "a"),
    subscript_vars=("info",),
    files=("gcs.py", "core_worker.py"),
    states=("DEPENDENCIES_UNREADY", "PENDING_CREATION", "ALIVE", "RESTARTING",
            "DEAD"),
    initial=("DEPENDENCIES_UNREADY", "PENDING_CREATION"),
    terminal=("DEAD",),
    quiescent=("ALIVE", "DEAD"),
    transitions=(
        ("DEPENDENCIES_UNREADY", "PENDING_CREATION", "gcs"),
        ("DEPENDENCIES_UNREADY", "DEAD", "gcs"),
        ("PENDING_CREATION", "ALIVE", "gcs"),
        ("PENDING_CREATION", "RESTARTING", "gcs"),
        ("PENDING_CREATION", "DEAD", "gcs"),
        ("ALIVE", "RESTARTING", "gcs"),
        ("ALIVE", "DEAD", "gcs"),
        ("RESTARTING", "ALIVE", "gcs"),
        ("RESTARTING", "RESTARTING", "gcs"),
        ("RESTARTING", "DEAD", "gcs"),
    ),
)

PLACEMENT_GROUP = Machine(
    name="placement-group",
    doc="GCS placement-group FSM (ray_tpu/_private/gcs.py, reference: "
    "gcs_placement_group_mgr.cc)",
    classes=("PlacementGroupInfo",),
    variables=("pg", "g"),
    subscript_vars=(),
    files=("gcs.py",),
    states=("PENDING", "CREATED", "RESCHEDULING", "REMOVED", "INFEASIBLE"),
    initial=("PENDING",),
    terminal=("REMOVED", "INFEASIBLE"),
    quiescent=("CREATED", "REMOVED", "INFEASIBLE"),
    transitions=(
        ("PENDING", "CREATED", "gcs"),
        ("PENDING", "INFEASIBLE", "gcs"),
        ("PENDING", "REMOVED", "client→gcs"),
        ("CREATED", "RESCHEDULING", "gcs (node death)"),
        ("CREATED", "REMOVED", "client→gcs"),
        ("RESCHEDULING", "CREATED", "gcs"),
        ("RESCHEDULING", "INFEASIBLE", "gcs"),
        ("RESCHEDULING", "REMOVED", "client→gcs"),
    ),
)

NODE = Machine(
    name="node",
    doc="GCS node-liveness FSM (ray_tpu/_private/gcs.py, reference: "
    "gcs_node_manager.cc). Nodes never resurrect: a rejoining host "
    "registers under a fresh node id.",
    classes=("NodeInfo",),
    variables=("node", "n"),
    subscript_vars=("n", "node"),
    files=("gcs.py", "raylet.py", "core_worker.py"),
    states=("ALIVE", "DEAD"),
    initial=("ALIVE",),
    terminal=("DEAD",),
    quiescent=("ALIVE", "DEAD"),
    transitions=(("ALIVE", "DEAD", "gcs (health check / conn drop)"),),
)

LEASE_LEDGER = Machine(
    name="lease-ledger",
    doc="raylet granted-lease dedup ledger (ray_tpu/_private/raylet.py): "
    "granted_lease_ids[lease_id] = True (LIVE) / False (RELEASED). "
    "Entries are evicted, never flipped back.",
    classes=(),
    variables=(),
    subscript_vars=(),
    files=("raylet.py",),
    states=("LIVE", "RELEASED"),
    initial=("LIVE", "RELEASED"),  # burn-on-arrival inserts RELEASED directly
    terminal=("RELEASED",),
    quiescent=("LIVE", "RELEASED"),
    transitions=(("LIVE", "RELEASED", "raylet"),),
)

OBJECT = Machine(
    name="object-location",
    doc="Location FSM of one primary object copy "
    "(ray_tpu/_private/raylet.py store side, "
    "ray_tpu/_private/core_worker.py owner side; see docs/object_plane.md). "
    "The raylet tracks the store-side states by set/dict membership "
    "(`spilling`, `spilled`, `restoring`) rather than a `.state` field, so "
    "the static extractor has no receivers to scan — this machine is "
    "enforced behaviorally: the chaos `store-settled` invariant rejects "
    "SPILLING/RESTORING after quiescence, and the spill suite's "
    "no-data-loss invariant exercises every edge, including the lost-copy "
    "paths. LOST is owner-observed (node-death pubsub or a failed "
    "restore); RECONSTRUCTING re-runs the producing TaskSpec from lineage.",
    classes=(),
    variables=(),
    subscript_vars=(),
    files=(),
    states=("LOCAL", "SPILLING", "SPILLED", "RESTORING", "LOST",
            "RECONSTRUCTING"),
    initial=("LOCAL",),
    terminal=(),
    quiescent=("LOCAL", "SPILLED", "LOST"),
    transitions=(
        ("LOCAL", "SPILLING", "raylet (pressure loop past "
         "object_spilling_threshold)"),
        ("SPILLING", "SPILLED", "raylet (external-storage write fsynced)"),
        ("SPILLING", "LOCAL", "raylet (spill aborted: freed or pinned "
         "mid-write)"),
        ("SPILLED", "RESTORING", "raylet (ObjGet miss or owner-directed "
         "RestoreSpilled)"),
        ("RESTORING", "LOCAL", "raylet (restore sealed back into the arena)"),
        ("RESTORING", "LOST", "raylet (SpillIntegrityError: torn file — "
         "copy dropped)"),
        ("LOCAL", "LOST", "owner (node-death pubsub: resident copy died)"),
        ("SPILLED", "LOST", "owner (node-death pubsub: spill namespace died "
         "with its node)"),
        ("LOST", "RECONSTRUCTING", "owner (lineage recovery re-submits the "
         "producing TaskSpec)"),
        ("RECONSTRUCTING", "LOCAL", "owner (producer re-ran; value is back)"),
        ("RECONSTRUCTING", "LOST", "owner (re-execution failed, depth cap, "
         "or lineage pruned → typed ObjectReconstructionFailedError)"),
    ),
)

MACHINES: Tuple[Machine, ...] = (
    ACTOR, PLACEMENT_GROUP, NODE, LEASE_LEDGER, OBJECT
)

# Attribute name whose subscript assignment drives the lease ledger.
_LEDGER_ATTR = "granted_lease_ids"
_BOOL_STATES = {True: "LIVE", False: "RELEASED"}


def _suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _spec_findings() -> List[Finding]:
    """Internal consistency of the spec itself (always checked)."""
    out: List[Finding] = []
    here = os.path.abspath(__file__)

    def bad(msg: str) -> None:
        out.append(Finding(here, 0, 0, RULE_ILLEGAL, f"spec error: {msg}"))

    for m in MACHINES:
        states = set(m.states)
        for group, name in ((m.initial, "initial"), (m.terminal, "terminal"),
                            (m.quiescent, "quiescent")):
            for s in group:
                if s not in states:
                    bad(f"{m.name}: {name} state {s!r} not in states")
        for src, dst, _driver in m.transitions:
            if src not in states or dst not in states:
                bad(f"{m.name}: transition {src}->{dst} uses unknown state")
            if src in m.terminal and src != dst:
                bad(f"{m.name}: terminal state {src} has outgoing edge to {dst}")
        for t in m.terminal:
            if t not in m.quiescent:
                bad(f"{m.name}: terminal state {t} missing from quiescent")
    return out


def check_invariants_sync(
    machine: Machine = ACTOR,
    invariant_states: Optional[Set[str]] = None,
) -> List[Finding]:
    """Cross-check the actor spec against the chaos convergence invariants.

    ``invariants.TERMINAL_ACTOR_STATES`` (the states chaos allows after
    quiescence) must equal the spec's quiescent set, and the spec's
    terminal states must survive quiescence — otherwise either chaos would
    flag legal end states as stuck, or the linter would bless states chaos
    rejects. Parameters exist so tests can inject drift.
    """
    import ray_tpu.chaos.invariants as inv

    if invariant_states is None:
        invariant_states = set(inv.TERMINAL_ACTOR_STATES)
    path = os.path.abspath(inv.__file__)
    line = 0
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for i, text in enumerate(fh, start=1):
                if "TERMINAL_ACTOR_STATES" in text:
                    line = i
                    break
    except OSError:
        pass
    out: List[Finding] = []
    spec_states = set(machine.quiescent)
    if spec_states != invariant_states:
        out.append(
            Finding(
                path, line, 0, RULE_DRIFT,
                f"chaos TERMINAL_ACTOR_STATES {sorted(invariant_states)} != "
                f"protocol spec quiescent({machine.name}) "
                f"{sorted(spec_states)} — update whichever is stale",
            )
        )
    for s in machine.terminal:
        if s not in invariant_states:
            out.append(
                Finding(
                    path, line, 0, RULE_DRIFT,
                    f"spec terminal state {s!r} of machine {machine.name!r} "
                    f"is not accepted by chaos TERMINAL_ACTOR_STATES — "
                    f"every terminal state must survive quiescence",
                )
            )
    return out


class _FileChecker(ast.NodeVisitor):
    """Extract and verify state assignments/comparisons in one file."""

    def __init__(self, tree: ast.Module, path: str) -> None:
        self.path = path
        self.base = os.path.basename(path)
        self.findings: List[Finding] = []
        self.class_stack: List[str] = []
        self.func_stack: List[str] = []
        # (machine name, receiver repr, possible source states)
        self.guards: List[Tuple[str, str, Set[str]]] = []
        self.consts: Dict[str, str] = {}
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.consts[node.targets[0].id] = node.value.value

    # -- resolution ---------------------------------------------------------

    def _machines_here(self) -> List[Machine]:
        return [m for m in MACHINES if self.base in m.files]

    def _state_expr(self, node: ast.AST) -> Optional[Tuple[Machine, str]]:
        """(machine, receiver repr) if ``node`` reads/writes a machine's
        state — ``recv.state``, ``recv["state"]``, or the ledger subscript."""
        if isinstance(node, ast.Attribute) and node.attr == "state":
            recv = node.value
            if isinstance(recv, ast.Name):
                for m in self._machines_here():
                    if recv.id == "self":
                        if self.class_stack and self.class_stack[-1] in m.classes:
                            return m, "self.state"
                    elif recv.id in m.variables:
                        return m, f"{recv.id}.state"
            return None
        if isinstance(node, ast.Subscript):
            key = node.slice
            if isinstance(key, ast.Constant) and key.value == "state":
                if isinstance(node.value, ast.Name):
                    for m in self._machines_here():
                        if node.value.id in m.subscript_vars:
                            return m, f'{node.value.id}["state"]'
            recv = _dotted(node.value)
            if recv and recv.rsplit(".", 1)[-1] == _LEDGER_ATTR:
                if self.base in LEASE_LEDGER.files:
                    return LEASE_LEDGER, f"{recv}[...]"
            return None
        return None

    def _resolve(self, node: ast.AST, machine: Machine) -> Tuple[Optional[str], bool]:
        """(state string or None, resolvable) for an assigned/compared value."""
        if isinstance(node, ast.Constant):
            if machine is LEASE_LEDGER and isinstance(node.value, bool):
                return _BOOL_STATES[node.value], True
            if isinstance(node.value, str):
                return node.value, True
            return None, False
        if isinstance(node, ast.Name) and node.id in self.consts:
            return self.consts[node.id], True
        return None, False

    # -- structure tracking -------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _guard_from_test(self, test: ast.AST) -> Optional[Tuple[str, str, Set[str]]]:
        """A state guard in an if/while test: ``recv.state == SRC`` or
        ``recv.state in (SRC, ...)`` — possibly one conjunct of an ``and``."""
        tests = test.values if isinstance(test, ast.BoolOp) and isinstance(
            test.op, ast.And) else [test]
        for t in tests:
            if not (isinstance(t, ast.Compare) and len(t.ops) == 1):
                continue
            se = self._state_expr(t.left)
            if se is None:
                continue
            machine, recv = se
            op = t.ops[0]
            comparator = t.comparators[0]
            if isinstance(op, ast.Eq):
                val, ok = self._resolve(comparator, machine)
                if ok and val in machine.states:
                    return machine.name, recv, {val}
            elif isinstance(op, ast.In) and isinstance(
                comparator, (ast.Tuple, ast.List, ast.Set)
            ):
                vals = set()
                for elt in comparator.elts:
                    val, ok = self._resolve(elt, machine)
                    if not ok:
                        break
                    vals.add(val)
                else:
                    if vals and vals <= set(machine.states):
                        return machine.name, recv, vals
        return None

    def _visit_guarded(self, node) -> None:
        self.visit(node.test)
        guard = self._guard_from_test(node.test)
        if guard is not None:
            self.guards.append(guard)
        for stmt in node.body:
            self.visit(stmt)
        if guard is not None:
            self.guards.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    visit_If = _visit_guarded
    visit_While = _visit_guarded

    # -- the checks ---------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.path, getattr(node, "lineno", 0),
                    getattr(node, "col_offset", 0), rule, msg)
        )

    def _check_assign(self, target: ast.AST, value: ast.AST,
                      node: ast.stmt) -> None:
        se = self._state_expr(target)
        if se is None:
            return
        machine, recv = se
        dst, ok = self._resolve(value, machine)
        if not ok:
            self._emit(
                node, RULE_UNRESOLVABLE,
                f"{recv} assigned a dynamic value — the {machine.name} "
                f"machine cannot verify this edge statically; use a "
                f"declared state constant or suppress with justification",
            )
            return
        if dst not in machine.states:
            self._emit(
                node, RULE_UNKNOWN,
                f"{recv} assigned {dst!r}, not a state of the "
                f"{machine.name} machine {list(machine.states)}",
            )
            return
        edges = {(s, d) for s, d, _ in machine.transitions}
        if (
            self.func_stack
            and self.func_stack[-1] == "__init__"
            and recv == "self.state"
        ):
            if dst not in machine.initial:
                self._emit(
                    node, RULE_ILLEGAL,
                    f"__init__ sets {recv} to {dst!r}, not an initial "
                    f"state of the {machine.name} machine "
                    f"{list(machine.initial)}",
                )
            return
        for g_machine, g_recv, sources in reversed(self.guards):
            if g_machine != machine.name or g_recv != recv:
                continue
            if dst in sources:
                return  # self-loop under the guard
            if not any((src, dst) in edges for src in sources):
                self._emit(
                    node, RULE_ILLEGAL,
                    f"{recv} set to {dst!r} under a guard proving state in "
                    f"{sorted(sources)}, but no transition "
                    f"{sorted(sources)}→{dst} is declared for the "
                    f"{machine.name} machine",
                )
            return
        # Unguarded: the edge source is unknown, so require that *some*
        # declared edge (or initial marking) can reach dst.
        if dst not in machine.initial and not any(d == dst for _, d in edges):
            self._emit(
                node, RULE_ILLEGAL,
                f"{recv} set to {dst!r}, but the {machine.name} machine "
                f"declares no transition into {dst!r} and it is not an "
                f"initial state",
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_assign(target, node.value, node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if len(node.ops) == 1:
            se = self._state_expr(node.left)
            if se is not None:
                machine, recv = se
                comparator = node.comparators[0]
                elts = (
                    comparator.elts
                    if isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and isinstance(comparator, (ast.Tuple, ast.List, ast.Set))
                    else [comparator]
                )
                for elt in elts:
                    val, ok = self._resolve(elt, machine)
                    if ok and val not in machine.states:
                        self._emit(
                            node, RULE_UNKNOWN,
                            f"{recv} compared against {val!r}, not a state "
                            f"of the {machine.name} machine "
                            f"{list(machine.states)}",
                        )
        self.generic_visit(node)


_SCANNED_BASENAMES = {b for m in MACHINES for b in m.files}


def check_source(
    source: str, path: str, apply_suppressions: bool = True
) -> List[Finding]:
    """Check one file's source; only files named like a scanned module
    (gcs.py / raylet.py / core_worker.py) produce findings."""
    if os.path.basename(path) not in _SCANNED_BASENAMES:
        return []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "parse-error", str(e.msg))]
    checker = _FileChecker(tree, path)
    checker.visit(tree)
    sup = _suppressions(source) if apply_suppressions else {}

    def suppressed(f: Finding) -> bool:
        for line in (f.line, f.line - 1):
            rules = sup.get(line)
            if rules and ("all" in rules or f.rule in rules):
                return True
        return False

    return sorted(
        (f for f in checker.findings if not suppressed(f)),
        key=lambda f: (f.line, f.col, f.rule),
    )


def check_file(path: str, apply_suppressions: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return check_source(fh.read(), path, apply_suppressions=apply_suppressions)


def check(
    paths: Optional[Iterable[str]] = None, apply_suppressions: bool = True
) -> List[Finding]:
    """Full pass: spec validation + file extraction + invariants sync."""
    paths = list(paths) if paths else [_default_root()]
    findings = _spec_findings()
    for path in paths:
        if os.path.isdir(path):
            for f in iter_py_files(path):
                findings.extend(check_file(f, apply_suppressions=apply_suppressions))
        else:
            findings.extend(check_file(path, apply_suppressions=apply_suppressions))
    try:
        findings.extend(check_invariants_sync())
    except ImportError:
        pass  # chaos subsystem not importable in this environment
    return findings


# -- documentation ----------------------------------------------------------


def markdown() -> str:
    """Render docs/protocols.md from the spec (deterministic)."""
    lines: List[str] = [
        "# Control-plane protocol state machines",
        "",
        "Generated from `ray_tpu/devtools/protocols.py` — do not edit by",
        "hand; run `make protocols` after changing the spec. The same spec",
        "drives the static checker (`python -m ray_tpu.devtools.protocols`,",
        "part of the `make lint` gate), so these tables are, by",
        "construction, what the linter enforces.",
        "",
    ]
    for m in MACHINES:
        lines += [f"## {m.name}", "", m.doc, ""]
        lines += [
            "| state | initial | terminal | quiescent |",
            "|---|---|---|---|",
        ]
        for s in m.states:
            lines.append(
                "| `{}` | {} | {} | {} |".format(
                    s,
                    "✓" if s in m.initial else "",
                    "✓" if s in m.terminal else "",
                    "✓" if s in m.quiescent else "",
                )
            )
        lines += [
            "",
            "| from | to | driven by |",
            "|---|---|---|",
        ]
        for src, dst, driver in m.transitions:
            lines.append(f"| `{src}` | `{dst}` | {driver} |")
        lines += ["", "```mermaid", "stateDiagram-v2"]
        for s in m.initial:
            lines.append(f"    [*] --> {s}")
        for src, dst, driver in m.transitions:
            lines.append(f"    {src} --> {dst}")
        for s in m.terminal:
            lines.append(f"    {s} --> [*]")
        lines += ["```", ""]
    lines += [
        "## Cross-checks",
        "",
        "- The actor machine's quiescent set must equal",
        "  `ray_tpu.chaos.invariants.TERMINAL_ACTOR_STATES` (the states the",
        "  chaos suite accepts after convergence); the checker fails with",
        "  `protocol-invariant-drift` if they diverge.",
        "- Every `.state = X` / `[\"state\"] = X` assignment in `gcs.py`,",
        "  `raylet.py`, and `core_worker.py` is verified against these",
        "  tables at lint time; dynamic assignments (restart restore paths)",
        "  carry `# protocol: disable=protocol-unresolvable` suppressions.",
        "",
    ]
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.protocols",
        description="protocol FSM checker (see module docstring for rules)",
    )
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument(
        "--markdown",
        action="store_true",
        help="print docs/protocols.md content instead of checking",
    )
    args = parser.parse_args(argv)
    if args.markdown:
        sys.stdout.write(markdown())
        return 0
    findings = check(args.paths or None)
    for f in findings:
        print(f)
    if findings:
        print(f"protocols: {len(findings)} finding(s)")
        return 1
    print("protocols: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
