"""Exhaustive interleaving explorer for the asyncio control plane.

Randomized chaos (``ray_tpu.chaos``) samples schedules; this module
*enumerates* them.  It virtualizes the asyncio event loop so that every
ready-callback wakeup and timer fire is an explicit *choice point*, then
drives a depth-first search over the schedule tree:

- ``VirtualLoop``: an ``asyncio.BaseEventLoop`` subclass with no selector,
  no self-pipe and no wall clock.  ``call_soon``/``call_at`` park labeled
  events in explorer-owned queues; ``time()`` reads a virtual clock that
  only advances when nothing runnable remains.  Every event gets a
  deterministic key ``<qualname>#<n>`` (task wakeups are labeled by the
  task's coroutine qualname), so a schedule is just a list of keys.
- ``Explorer``: sleep-set pruned DFS (Godefroid-style; the DPOR flavour
  where commuting independent wakeups are explored once).  Independence
  comes from the static ``aio_lint`` shared-attribute footprints: two
  events commute iff their code's read/write sets on shared containers are
  disjoint.  Unknown or same-qualname events are conservatively dependent.
  ``--naive`` disables pruning for A/B comparison.
- Replay: any schedule (in particular a violating one) serializes to a
  JSON choice trace and replays byte-identically; divergence between the
  recorded enabled sets and a replay is itself reported as a determinism
  failure.  Traces for regression tests live under ``tests/schedules/``.
- Crash-point enumeration (``crash_scan_wal`` / ``crash_scan_replicated``):
  run a store workload once, snapshot the table state at every
  group-commit boundary, then for each commit reopen the log truncated at
  that boundary (plus a torn-tail variant) and prove recovery lands
  exactly on the acknowledged prefix.

Scenarios are registered in ``ray_tpu.chaos.scenarios_explore`` and share
the chaos invariant checks.  CLI::

    python -m ray_tpu.devtools.explore --list
    python -m ray_tpu.devtools.explore --scenario all --budget 20000
    python -m ray_tpu.devtools.explore --scenario lease_exactly_once \
        --mutate double_grant --expect-violation --save-trace /tmp/t.json
    python -m ray_tpu.devtools.explore --replay tests/schedules/x.json
    python -m ray_tpu.devtools.explore --crash-points

The footprint approximation is intentionally conservative but not
transitively complete across classes (see docs/static_analysis.md); the
``--naive`` mode is the ground truth the DPOR mode is tested against.
"""

from __future__ import annotations

import argparse
import asyncio
import functools
import hashlib
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

TRACE_FORMAT = 1


class ExploreError(Exception):
    """Engine-level failure (divergence, deadlock, budget exhaustion)."""


class NondeterminismError(ExploreError):
    """A replayed prefix produced a different enabled set."""


class DeadlockError(ExploreError):
    """No runnable event and no pending timer, but the root task is live."""


# ---------------------------------------------------------------------------
# Virtual event loop
# ---------------------------------------------------------------------------


class _Event:
    """One schedulable unit: a parked ``Handle`` plus its stable label."""

    __slots__ = ("key", "handle", "when", "seq")

    def __init__(self, key: str, handle: asyncio.Handle, when: Optional[float], seq: int):
        self.key = key
        self.handle = handle
        self.when = when  # None for ready callbacks, virtual time for timers
        self.seq = seq

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<_Event {self.key} when={self.when}>"


def _callback_qualname(cb: Any) -> str:
    """Deterministic label for a loop callback (no memory addresses)."""
    while isinstance(cb, functools.partial):
        cb = cb.func
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        coro = owner.get_coro()
        qual = getattr(coro, "__qualname__", None) or type(coro).__name__
        return f"task:{qual}"
    qual = getattr(cb, "__qualname__", None)
    if qual is None:
        qual = type(cb).__name__
    return f"cb:{qual}"


#: Callback labels that are pure container bookkeeping — they neither read
#: protocol state nor unblock any coroutine, so their placement in the
#: schedule is unobservable.  The loop dispatches them eagerly instead of
#: offering them as choice points (``set.discard``/``set.add`` come from
#: task-registry done-callbacks such as ``rpc._BG_TASKS.discard``).
_BOOKKEEPING_LABELS = ("cb:set.discard#", "cb:set.add#")


def _is_bookkeeping(key: str) -> bool:
    return key.startswith(_BOOKKEEPING_LABELS)


class VirtualLoop(asyncio.BaseEventLoop):
    """A fully controlled event loop: nothing runs until the explorer says so.

    ``BaseEventLoop`` (not ``SelectorEventLoop``) on purpose: the selector
    flavour allocates a selector plus a self-pipe socketpair per instance,
    and the explorer constructs thousands of loops per enumeration.  This
    subclass opens no file descriptors at all.
    """

    def __init__(self) -> None:
        super().__init__()
        self._vclock = 0.0
        self._seq = 0
        self._label_counts: Dict[str, int] = {}
        self._ready_events: List[_Event] = []
        self._timer_events: List[_Event] = []
        self.exceptions: List[BaseException] = []

    # -- event capture ------------------------------------------------------

    def _park(self, handle: asyncio.Handle, cb: Any, when: Optional[float]) -> None:
        label = _callback_qualname(cb)
        n = self._label_counts.get(label, 0)
        self._label_counts[label] = n + 1
        self._seq += 1
        ev = _Event(f"{label}#{n}", handle, when, self._seq)
        if when is None:
            self._ready_events.append(ev)
        else:
            self._timer_events.append(ev)

    def call_soon(self, callback, *args, context=None):
        handle = asyncio.Handle(callback, args, self, context)
        self._park(handle, callback, None)
        return handle

    def call_soon_threadsafe(self, callback, *args, context=None):
        return self.call_soon(callback, *args, context=context)

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self._vclock + max(0.0, delay), callback, *args, context=context)

    def call_at(self, when, callback, *args, context=None):
        handle = asyncio.TimerHandle(when, callback, args, self, context)
        self._park(handle, callback, when)
        return handle

    def time(self) -> float:
        return self._vclock

    # BaseEventLoop's _timer_handle_cancelled only bookkeeps handles it
    # scheduled itself (``_scheduled`` flag); ours never set it, so the
    # inherited no-op behaviour is correct.

    # -- scheduling surface consumed by the explorer ------------------------

    def enabled_events(self) -> List[_Event]:
        """Runnable events, deterministic order: ready FIFO then due timers."""
        self._ready_events = [e for e in self._ready_events if not e.handle._cancelled]
        self._timer_events = [e for e in self._timer_events if not e.handle._cancelled]
        due = [e for e in self._timer_events if e.when <= self._vclock + 1e-9]
        due.sort(key=lambda e: (e.when, e.seq))
        return self._ready_events + due

    def advance_clock(self) -> bool:
        """Jump to the next timer deadline; False if no timers pending."""
        self._timer_events = [e for e in self._timer_events if not e.handle._cancelled]
        if not self._timer_events:
            return False
        self._vclock = min(e.when for e in self._timer_events)
        return True

    def dispatch(self, ev: _Event) -> None:
        try:
            self._ready_events.remove(ev)
        except ValueError:
            self._timer_events.remove(ev)
        if not ev.handle._cancelled:
            ev.handle._run()

    def call_exception_handler(self, context) -> None:
        exc = context.get("exception")
        if exc is not None:
            self.exceptions.append(exc)

    # -- drive / drain ------------------------------------------------------

    def drive(self, coro, chooser: Callable[[List[_Event]], _Event], max_steps: int) -> Any:
        """Run ``coro`` to completion, delegating every choice to ``chooser``.

        Returns the coroutine's result; raises its exception; raises
        ``DeadlockError``/``ExploreError`` on stuck or over-budget runs.
        """
        asyncio.events._set_running_loop(self)
        try:
            root = asyncio.tasks.Task(coro, loop=self)
            try:
                steps = 0
                while not root.done():
                    enabled = self.enabled_events()
                    auto = next(
                        (e for e in enabled if _is_bookkeeping(e.key)), None
                    )
                    if auto is not None:
                        # GC-registry bookkeeping (task done-callbacks like
                        # rpc._BG_TASKS.discard) commutes with every protocol
                        # transition: running it eagerly collapses a
                        # factorial blowup without hiding any interleaving.
                        self.dispatch(auto)
                        continue
                    if not enabled:
                        if self.advance_clock():
                            continue
                        raise DeadlockError(
                            "no runnable events and no pending timers but "
                            "the scenario has not finished"
                        )
                    steps += 1
                    if steps > max_steps:
                        raise ExploreError(
                            f"schedule exceeded max_steps={max_steps}"
                        )
                    self.dispatch(chooser(enabled))
            except BaseException:
                # Consume the root coroutine (and any tasks it spawned) so
                # abandoned schedules don't leak never-awaited coroutines.
                root.cancel()
                self._shutdown()
                raise
            self._shutdown()
            return root.result()
        finally:
            asyncio.events._set_running_loop(None)

    def _shutdown(self) -> None:
        """Cancel abandoned background tasks and drain their wakeups."""
        for task in asyncio.tasks.all_tasks(self):
            if not task.done():
                task.cancel()
        self._drain_fifo()
        self._timer_events = []

    def _drain_fifo(self, rounds: int = 64) -> None:
        for _ in range(rounds):
            self._ready_events = [
                e for e in self._ready_events if not e.handle._cancelled
            ]
            if not self._ready_events:
                break
            batch, self._ready_events = self._ready_events, []
            for ev in batch:
                if not ev.handle._cancelled:
                    ev.handle._run()


# ---------------------------------------------------------------------------
# Independence oracle (static aio_lint footprints)
# ---------------------------------------------------------------------------


class IndependenceOracle:
    """Decide whether two events commute, from static read/write footprints.

    ``footprints`` maps a function qualname (``Cls.method`` or module-level
    name) to ``{"reads": set, "writes": set}`` over shared-container keys.
    Missing qualnames and identical qualnames are conservatively dependent.
    """

    def __init__(self, footprints: Dict[str, Dict[str, Set[str]]]):
        self.footprints = footprints

    @staticmethod
    def qual_of(key: str) -> str:
        label = key.rsplit("#", 1)[0]
        return label.split(":", 1)[1] if ":" in label else label

    def independent(self, key_a: str, key_b: str) -> bool:
        qa, qb = self.qual_of(key_a), self.qual_of(key_b)
        if qa == qb:
            return False
        fa = self.footprints.get(qa)
        fb = self.footprints.get(qb)
        if fa is None or fb is None:
            return False
        if fa["writes"] & (fb["reads"] | fb["writes"]):
            return False
        if fb["writes"] & fa["reads"]:
            return False
        return True


_REPO_FOOTPRINTS: Optional[Dict[str, Dict[str, Set[str]]]] = None


def repo_footprints() -> Dict[str, Dict[str, Set[str]]]:
    """Shared-attribute footprints for the whole package (cached)."""
    global _REPO_FOOTPRINTS
    if _REPO_FOOTPRINTS is None:
        from ray_tpu.devtools import aio_lint

        _REPO_FOOTPRINTS = aio_lint.extract_footprints([aio_lint._default_root()])
    return _REPO_FOOTPRINTS


# ---------------------------------------------------------------------------
# Sleep-set DFS explorer
# ---------------------------------------------------------------------------


@dataclass
class _Frame:
    enabled: List[str]
    chosen: str = ""
    tried: Set[str] = field(default_factory=set)
    sleep: Set[str] = field(default_factory=set)


@dataclass
class RunRecord:
    status: str  # "ok" | "violation" | "pruned"
    choices: List[str]
    violations: List[str]


@dataclass
class ExploreReport:
    scenario: str
    schedules: int = 0
    pruned: int = 0
    violations: int = 0
    complete: bool = False
    stopped_on_violation: bool = False
    first_violation: Optional[RunRecord] = None
    digest: str = ""

    def summary(self) -> str:
        if self.complete:
            state = "exhausted"
        elif self.stopped_on_violation:
            state = "stopped at first violation"
        else:
            state = "BUDGET EXCEEDED"
        return (
            f"{self.scenario}: {self.schedules} schedules ({state}), "
            f"{self.pruned} pruned, {self.violations} violation(s), "
            f"digest {self.digest[:16]}"
        )


class _PruneRun(Exception):
    """Internal: every enabled event at this node is in the sleep set."""


class Explorer:
    """Depth-first schedule enumeration with sleep-set pruning.

    ``scenario_factory`` builds a fresh scenario instance per run; the
    instance exposes ``async run() -> List[str]`` (violation strings) and a
    sync ``cleanup()``.  Each run replays the choice prefix on the frame
    stack and extends it with the default policy (first enabled event not
    in the node's sleep set); backtracking forces the next untried
    candidate at the deepest incomplete frame.
    """

    def __init__(
        self,
        scenario_factory: Callable[[], Any],
        oracle: Optional[IndependenceOracle] = None,
        dpor: bool = True,
        max_steps: int = 5000,
    ):
        self.scenario_factory = scenario_factory
        self.oracle = oracle
        self.dpor = dpor and oracle is not None
        self.max_steps = max_steps
        self.stack: List[_Frame] = []
        self._redo_depth: Optional[int] = None
        self._redo_choice: Optional[str] = None
        self._hash = hashlib.sha256()

    def _run_once(self) -> RunRecord:
        loop = VirtualLoop()
        inst = self.scenario_factory()
        depth = 0  # index into the branching-frame stack, not the step count
        cur_sleep: Set[str] = set()
        choices: List[str] = []
        pruned = False

        def wake(sleep: Set[str], executed: str) -> Set[str]:
            """Executing a transition wakes every dependent slept event."""
            if not sleep or not self.dpor:
                return set()
            assert self.oracle is not None
            return {x for x in sleep if self.oracle.independent(x, executed)}

        def chooser(enabled: List[_Event]) -> _Event:
            nonlocal depth, cur_sleep
            keys = [e.key for e in enabled]
            if all(k in cur_sleep for k in keys):
                # Every continuation is slept: this whole subtree is
                # equivalent to one explored elsewhere.
                raise _PruneRun()
            if len(enabled) == 1:
                # Forced move, not a choice point: no frame, but it still
                # wakes dependent slept events.
                ev = enabled[0]
                cur_sleep = wake(cur_sleep, ev.key)
                choices.append(ev.key)
                return ev
            if depth < len(self.stack):
                frame = self.stack[depth]
                if frame.enabled != keys:
                    raise NondeterminismError(
                        f"replay divergence at branch {depth}: recorded "
                        f"{frame.enabled} vs observed {keys}"
                    )
                if depth == self._redo_depth:
                    assert self._redo_choice is not None
                    frame.chosen = self._redo_choice
                    frame.tried.add(self._redo_choice)
                cur_sleep = set(frame.sleep)
            else:
                frame = _Frame(enabled=keys, sleep=set(cur_sleep))
                candidates = [k for k in keys if k not in frame.sleep]
                frame.chosen = candidates[0]
                frame.tried.add(frame.chosen)
                self.stack.append(frame)
            depth += 1
            cur_sleep = wake(
                (frame.sleep | frame.tried) - {frame.chosen}, frame.chosen
            )
            choices.append(frame.chosen)
            for ev in enabled:
                if ev.key == frame.chosen:
                    return ev
            raise NondeterminismError(
                f"recorded choice {frame.chosen!r} not enabled at branch "
                f"{depth - 1}: {keys}"
            )

        try:
            violations = loop.drive(inst.run(), chooser, self.max_steps)
        except _PruneRun:
            pruned = True
            violations = []
        except (asyncio.CancelledError, DeadlockError) as exc:
            if isinstance(exc, DeadlockError):
                violations = [f"deadlock: {exc}"]
            else:
                violations = ["scenario cancelled unexpectedly"]
        except ExploreError:
            raise
        except BaseException as exc:  # scenario bug is a finding, not a crash
            violations = [f"exception: {type(exc).__name__}: {exc}"]
        finally:
            try:
                inst.cleanup()
            finally:
                loop.close()
        if not pruned:
            for exc in loop.exceptions:
                violations.append(
                    f"background exception: {type(exc).__name__}: {exc}"
                )
        status = "pruned" if pruned else ("violation" if violations else "ok")
        return RunRecord(status=status, choices=choices, violations=violations)

    def explore(
        self,
        name: str,
        budget: int = 50000,
        stop_on_violation: bool = False,
    ) -> ExploreReport:
        report = ExploreReport(scenario=name)
        runs = 0
        while True:
            if runs >= budget:
                report.complete = False
                break
            runs += 1
            rec = self._run_once()
            self._redo_depth = self._redo_choice = None
            self._hash.update(
                ("|".join(rec.choices) + "::" + rec.status).encode()
            )
            if rec.status == "pruned":
                report.pruned += 1
            else:
                report.schedules += 1
                if rec.status == "violation":
                    report.violations += 1
                    if report.first_violation is None:
                        report.first_violation = rec
                    if stop_on_violation:
                        # Mutation-gate mode: the first witness schedule is
                        # the deliverable; the rest of the space is moot.
                        report.complete = False
                        report.stopped_on_violation = True
                        break
            # Backtrack to the deepest frame with an untried, unslept branch.
            redo: Optional[Tuple[int, str]] = None
            while self.stack:
                f = self.stack[-1]
                cands = [
                    k for k in f.enabled if k not in f.tried and k not in f.sleep
                ]
                if cands:
                    redo = (len(self.stack) - 1, cands[0])
                    break
                self.stack.pop()
            if redo is None:
                report.complete = True
                break
            self._redo_depth, self._redo_choice = redo
        report.digest = self._hash.hexdigest()
        return report


# ---------------------------------------------------------------------------
# Trace replay
# ---------------------------------------------------------------------------


def save_trace(path: str, scenario: str, rec: RunRecord, mutations: Sequence[str]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {
                "format": TRACE_FORMAT,
                "scenario": scenario,
                "mutations": list(mutations),
                "status": rec.status,
                "violations": rec.violations,
                "trace": rec.choices,
            },
            fh,
            indent=2,
        )
        fh.write("\n")


def load_trace(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("format") != TRACE_FORMAT:
        raise ExploreError(f"unsupported trace format in {path}: {data.get('format')}")
    return data


def replay(scenario_factory: Callable[[], Any], trace: Sequence[str], max_steps: int = 5000) -> RunRecord:
    """Re-execute one schedule from its serialized choice list."""
    loop = VirtualLoop()
    inst = scenario_factory()
    cursor = 0
    choices: List[str] = []

    def chooser(enabled: List[_Event]) -> _Event:
        nonlocal cursor
        if cursor >= len(trace):
            raise NondeterminismError(
                f"trace exhausted after {cursor} choices but scenario still "
                f"runnable (enabled: {[e.key for e in enabled]})"
            )
        want = trace[cursor]
        cursor += 1
        for ev in enabled:
            if ev.key == want:
                choices.append(want)
                return ev
        raise NondeterminismError(
            f"trace step {cursor - 1} wants {want!r} but enabled events are "
            f"{[e.key for e in enabled]}"
        )

    try:
        violations = loop.drive(inst.run(), chooser, max_steps)
    except NondeterminismError:
        raise
    except ExploreError:
        raise
    except BaseException as exc:
        violations = [f"exception: {type(exc).__name__}: {exc}"]
    finally:
        try:
            inst.cleanup()
        finally:
            loop.close()
    for exc in loop.exceptions:
        violations.append(f"background exception: {type(exc).__name__}: {exc}")
    return RunRecord(
        status="violation" if violations else "ok",
        choices=choices,
        violations=violations,
    )


# ---------------------------------------------------------------------------
# Crash-point enumeration
# ---------------------------------------------------------------------------


@dataclass
class CrashReport:
    backend: str
    commits: int = 0
    cases: int = 0
    failures: List[str] = field(default_factory=list)

    def summary(self) -> str:
        verdict = "all durable" if not self.failures else f"{len(self.failures)} FAILURE(S)"
        return (
            f"crash-points[{self.backend}]: {self.commits} commits, "
            f"{self.cases} crash cases, {verdict}"
        )


def crash_scan_wal(workdir: str, workload: Optional[Callable[[Any], None]] = None) -> CrashReport:
    """Enumerate WalStore crash points: one truncated + one torn-tail case
    per group-commit boundary; recovery must land on the acked prefix."""
    import copy
    import os
    import shutil

    from ray_tpu._private import gcs_store

    report = CrashReport(backend="wal")
    log = os.path.join(workdir, "wal-crash.log")
    snapshots: List[Tuple[int, Dict[str, Dict[bytes, bytes]]]] = []

    store = gcs_store.WalStoreClient(log, sync="off")
    store.commit_listener = lambda commit, offset, n_ops: snapshots.append(
        (offset, copy.deepcopy(store._tables))
    )
    if workload is None:
        def workload(st):
            for i in range(6):
                st.put("t", f"k{i}", b"v%d" % i)
                st.flush()
                if i % 2 == 1:
                    st.delete("t", f"k{i - 1}")
                    st.flush()
    workload(store)
    store.commit_listener = None
    store.close()

    report.commits = len(snapshots)
    for idx, (offset, tables) in enumerate(snapshots):
        for torn in (False, True):
            case = os.path.join(workdir, f"wal-case-{idx}-{int(torn)}.log")
            shutil.copyfile(log, case)
            with open(case, "r+b") as fh:
                fh.truncate(offset)
            if torn:
                gcs_store.inject_torn_tail(case)
            recovered = gcs_store.WalStoreClient(case, sync="off")
            try:
                report.cases += 1
                if recovered._tables != tables:
                    report.failures.append(
                        f"commit {idx} (torn={torn}): recovered state does "
                        f"not match acked snapshot"
                    )
            finally:
                recovered.close()
            os.unlink(case)
    return report


def crash_scan_replicated(workdir: str) -> CrashReport:
    """Quorum-replicated crash points over a 3-member group with a rotating
    partitioned laggard. At every quorum commit boundary the member files
    are imaged; for each image we enumerate losing each single member ×
    {clean, torn-tail-on-survivors} and run the quorum-freshest election
    (max (term, seq) via _parse_replicated) over the two survivors. Every
    acknowledged write must appear in the elected state — the on-disk
    proof of the ack-quorum ∩ election-majority intersection argument.
    Writes *after* the imaged commit may legitimately be lost."""
    import os
    import shutil

    from ray_tpu._private import gcs_store

    report = CrashReport(backend="replicated")
    primary = os.path.join(workdir, "repl-crash.log")
    followers = [
        os.path.join(workdir, "repl-crash.follower0"),
        os.path.join(workdir, "repl-crash.follower1"),
    ]
    members = [primary] + followers
    acked: List[Set[str]] = []
    images: List[List[str]] = []
    written: List[str] = []

    store = gcs_store.ReplicatedStoreClient(
        primary, followers=followers, term=1, sync="off"
    )

    def on_commit(seq: int, n_ops: int) -> None:
        # Image every member file at the commit boundary. A partitioned or
        # lagging member's copy may be stale or mid-append (torn) — that is
        # the point: the election must not need it.
        idx = len(images)
        image = []
        for mi, path in enumerate(members):
            copy_path = os.path.join(workdir, f"repl-case-{idx}.m{mi}")
            shutil.copyfile(path, copy_path)
            image.append(copy_path)
        images.append(image)
        acked.append(set(written))

    store.commit_listener = on_commit
    # Rotate a minority partition across the followers: commits 0-2 with
    # follower0 dark, 3-5 with follower1 dark (follower0 catches up via a
    # snapshot frame), 6-9 fully healed. Quorum (2 of 3) must keep acking
    # throughout.
    schedule = {0: followers[0], 3: followers[1], 6: None}
    try:
        for i in range(10):
            if i in schedule:
                gcs_store.heal_all_partitions()
                if schedule[i] is not None:
                    gcs_store.partition_host(schedule[i])
            key = f"rk{i}"
            store.put("t", key, b"rv%d" % i)
            written.append(key)
            store.flush()
    finally:
        store.commit_listener = None
        store.close()
        gcs_store.heal_all_partitions()

    report.commits = len(images)
    for idx, image in enumerate(images):
        for lost in range(len(members)):
            survivors = [p for mi, p in enumerate(image) if mi != lost]
            for torn in (False, True):
                report.cases += 1
                states = []
                for sp in survivors:
                    case = sp + (".torn" if torn else ".clean")
                    shutil.copyfile(sp, case)
                    if torn:
                        gcs_store.inject_torn_tail(case)
                    with open(case, "rb") as fh:
                        data = fh.read()
                    states.append(gcs_store._parse_replicated(data))
                    os.unlink(case)
                tables, term, seq, _ = max(states, key=lambda s: (s[1], s[2]))
                have = set(tables.get("t", {}).keys())
                missing = acked[idx] - have
                if missing:
                    report.failures.append(
                        f"commit {idx} (lost=m{lost}, torn={torn}): acked "
                        f"keys missing from elected state: {sorted(missing)}"
                    )
        for copy_path in image:
            os.unlink(copy_path)
    return report


# ---------------------------------------------------------------------------
# Virtual in-memory RPC transport (for protocol scenarios)
# ---------------------------------------------------------------------------


class _VirtualTransport(asyncio.Transport):
    """Loopback transport: writes become ``call_soon`` deliveries on the
    peer protocol, so every frame delivery is an explorer choice point."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        super().__init__()
        self._loop = loop
        self.peer: Optional[Any] = None  # peer protocol
        self._closing = False

    def write(self, data: bytes) -> None:
        if self._closing or self.peer is None:
            return
        self._loop.call_soon(self.peer.data_received, bytes(data))

    def is_closing(self) -> bool:
        return self._closing

    def close(self) -> None:
        if self._closing:
            return
        self._closing = True
        if self.peer is not None:
            self._loop.call_soon(self.peer.connection_lost, None)

    def abort(self) -> None:
        self.close()

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        return default


def virtual_connection_pair(client_handlers: Dict[str, Any], server_handlers: Dict[str, Any]) -> Tuple[Any, Any]:
    """Two ``rpc.Connection``s wired back-to-back entirely in memory.

    Must be called with the virtual loop running (Connection's ctor
    requires a running loop).  Returns ``(client_conn, server_conn)``.
    """
    from ray_tpu._private import rpc

    loop = asyncio.get_running_loop()
    client = rpc.Connection(handlers=client_handlers)
    server = rpc.Connection(handlers=server_handlers)
    t_client = _VirtualTransport(loop)
    t_server = _VirtualTransport(loop)
    t_client.peer = server._protocol
    t_server.peer = client._protocol
    client._protocol.connection_made(t_client)
    server._protocol.connection_made(t_server)
    return client, server


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _scenario_registry() -> Dict[str, Any]:
    from ray_tpu.chaos import scenarios_explore

    return scenarios_explore.SCENARIOS


def _build_explorer(spec: Any, naive: bool, max_steps: int, mutations: Sequence[str]) -> Explorer:
    oracle = None if naive else IndependenceOracle(repo_footprints())
    return Explorer(
        scenario_factory=lambda: spec.factory(mutations=list(mutations)),
        oracle=oracle,
        dpor=not naive,
        max_steps=max_steps,
    )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.explore",
        description="exhaustive interleaving explorer (see module docstring)",
    )
    parser.add_argument("--list", action="store_true", help="list scenarios")
    parser.add_argument("--scenario", default=None, help="scenario name or 'all'")
    parser.add_argument("--budget", type=int, default=50000, help="max schedules per scenario")
    parser.add_argument("--max-steps", type=int, default=5000, help="max events per schedule")
    parser.add_argument("--naive", action="store_true", help="disable sleep-set pruning")
    parser.add_argument(
        "--mutate",
        action="append",
        default=[],
        help="enable a seeded bug (e.g. double_grant) — the explorer must catch it",
    )
    parser.add_argument(
        "--expect-violation",
        action="store_true",
        help="exit 0 iff at least one violation is found (mutation gate)",
    )
    parser.add_argument(
        "--allow-bounded",
        action="store_true",
        help="a clean run that exhausts the budget without exhausting the "
        "space still exits 0 (for spaces too big for the CI budget)",
    )
    parser.add_argument("--save-trace", default=None, help="write first violating schedule to FILE")
    parser.add_argument("--replay", default=None, help="replay a serialized choice trace")
    parser.add_argument(
        "--check-determinism",
        action="store_true",
        help="run each enumeration twice and require identical digests",
    )
    parser.add_argument(
        "--crash-points",
        action="store_true",
        help="enumerate WalStore/ReplicatedStore crash points instead of schedules",
    )
    args = parser.parse_args(argv)

    if args.crash_points:
        import tempfile
        import shutil as _shutil

        tmp = tempfile.mkdtemp(prefix="explore-crash-")
        try:
            reports = [crash_scan_wal(tmp), crash_scan_replicated(tmp)]
        finally:
            _shutil.rmtree(tmp, ignore_errors=True)
        bad = False
        for rep in reports:
            print(rep.summary())
            for f in rep.failures:
                print(f"  FAIL: {f}")
                bad = True
        return 1 if bad else 0

    registry = _scenario_registry()
    if args.list:
        for name, spec in sorted(registry.items()):
            print(f"{name}: {spec.description}")
        return 0

    if args.replay:
        data = load_trace(args.replay)
        name = data["scenario"]
        if name not in registry:
            print(f"explore: unknown scenario in trace: {name}", file=sys.stderr)
            return 2
        spec = registry[name]
        mutations = data.get("mutations", [])
        rec = replay(
            lambda: spec.factory(mutations=mutations),
            data["trace"],
            max_steps=args.max_steps,
        )
        print(f"replay {name} ({len(rec.choices)} choices): {rec.status}")
        for v in rec.violations:
            print(f"  violation: {v}")
        if args.expect_violation:
            return 0 if rec.status == "violation" else 1
        return 0 if rec.status == "ok" else 1

    if not args.scenario:
        parser.print_usage()
        return 2
    names = sorted(registry) if args.scenario == "all" else [args.scenario]
    exit_code = 0
    for name in names:
        if name not in registry:
            print(f"explore: unknown scenario {name!r}", file=sys.stderr)
            return 2
        spec = registry[name]
        explorer = _build_explorer(spec, args.naive, args.max_steps, args.mutate)
        report = explorer.explore(
            name,
            budget=args.budget,
            stop_on_violation=args.expect_violation,
        )
        if args.check_determinism:
            second = _build_explorer(spec, args.naive, args.max_steps, args.mutate)
            report2 = second.explore(name, budget=args.budget)
            if report.digest != report2.digest:
                print(f"{name}: NONDETERMINISTIC enumeration "
                      f"({report.digest[:16]} vs {report2.digest[:16]})")
                exit_code = 1
            else:
                print(f"{name}: deterministic across two runs")
        print(report.summary())
        if report.first_violation is not None:
            for v in report.first_violation.violations:
                print(f"  violation: {v}")
            if args.save_trace:
                save_trace(args.save_trace, name, report.first_violation, args.mutate)
                print(f"  trace saved to {args.save_trace}")
        if args.expect_violation:
            if report.violations == 0:
                print(f"{name}: expected a violation but found none")
                exit_code = 1
        else:
            if report.violations:
                exit_code = 1
            elif not report.complete and not args.allow_bounded:
                exit_code = 1
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
